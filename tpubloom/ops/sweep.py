"""Pallas dense partition-sweep insert — the TPU hot-loop escape hatch.

Why this exists: XLA's scatter on TPU applies row updates ~serially
(~100ns/row measured on v5e), so the sorted-unique row scatter in
:func:`tpubloom.ops.blocked.blocked_insert` caps batched inserts at
~7M rows/sec regardless of bandwidth. This kernel replaces the scatter
with work the TPU is actually built for:

1. keys are sorted by owning block (``lax.sort`` — cheap, ~3ms/1M on
   v5e for 3 columns);
2. the block array is streamed HBM -> VMEM -> HBM **once per batch** in
   ``R``-row partitions (the Pallas grid pipeline double-buffers this
   stream automatically);
3. each partition's updates (a contiguous slice of the sorted key
   stream, located via precomputed partition boundaries and fetched
   with double-buffered manual DMA) are merged entirely in UPDATE space
   ([KMAX, *] — nothing here scales with R*block_bits) by **exact
   one-hot matmuls on the MXU**, then placed with one weight-1 term per
   touched row. See ``_kernel``'s chunk_delta for the stage list.

Exactness rules (every matmul runs as bf16 passes on the MXU):
operands are 0/1 one-hots, power-of-two weights, or values <= 255
(8-bit "quarter" splits of packed words) — all bf16-integer-exact —
with f32 accumulation. Packing/unpacking/transposing are themselves
matmuls against constant weight matrices because Mosaic supports
neither sublane<->lane reshapes, nor static lane slicing, nor sublane
shifts (the latter two MISCOMPILE silently — every workaround here was
validated against the XLA scatter path on real TPU).

Variants sharing the machinery:
* plain insert (``make_sweep_insert_fn`` / ``apply_blocked_updates``,
  also the per-device hot loop of the sharded filter);
* fused test-and-insert (``with_presence``): pre-batch membership is
  extracted from the old tile during the same pass and returned in
  original key order via a single-column unsort sort;
* blocked-counting update (``_count_kernel``): saturating 4-bit
  nibble add/subtract, no merge stage (counts are additive).

Measured on v5e at m=2^32, k=7, B=4M: 20.1M fused insert+query
keys/s vs 5.5M for the XLA sorted-scatter path — with bit-identical
results (same blocked position spec as :mod:`tpubloom.ops.blocked`;
the CPU oracle is the shared ground truth).

Adversarial skew (duplicate keys, tiny filters) is handled by an
in-kernel chunk loop: a partition with more than KMAX updates fetches
and merges ceil(n/KMAX) chunks serially. Batch-padding keys carry the
sentinel block id ``n_blocks`` and sort past every real partition.

Parity: reference hot path is SETBIT-per-position against the m-bit
array (BASELINE.json north_star); this is that hot loop, restructured
as sort + dense sweep because random-access SETBIT is precisely what
TPU HBM cannot do fast.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpubloom.ops import blocked


class InFlight:
    """Depth-1 host-side double buffer (ISSUE 10).

    The Pallas grid pipeline double-buffers the HBM stream *inside* one
    kernel; this is the same idea one level up, for the host feed: a
    batching driver (the server's ingestion coalescer, bench loops)
    launches batch N unfenced, parks ``(handle, payload)`` here, stages
    batch N+1's host_prep/H2D while N's kernel runs, and only then
    calls :meth:`take` — which fences N and hands back its payload for
    completion. JAX async dispatch does the actual overlap; this class
    just keeps the bookkeeping (and the fence) in one place.
    """

    def __init__(self):
        self._handle = None
        self._payload = None

    @property
    def pending(self) -> bool:
        return self._payload is not None

    def put(self, handle, payload):
        """Park one launched batch; returns the PREVIOUS batch's
        ``(payload, fence_error)`` pair fenced (``(None, None)`` when
        nothing was in flight) — see :meth:`take`."""
        prev = self.take()
        self._handle, self._payload = handle, payload
        return prev

    def take(self):
        """Fence and return ``(payload, fence_error)`` — both None when
        idle. The donated-buffer case is BENIGN and swallowed: with
        ``donate_argnums`` a later kernel on the same state consumes
        (deletes) this handle's buffer, and ``block_until_ready`` on a
        donated buffer raises instead of waiting — but the data
        dependency already guarantees this kernel completed before its
        consumer does. Any OTHER fence error (device OOM, a real kernel
        failure) is RETURNED, not raised or swallowed: the caller must
        fail the batch's waiters rather than ack work that never
        happened."""
        if self._payload is None:
            return None, None
        handle, payload = self._handle, self._payload
        self._handle = self._payload = None
        err = None
        if handle is not None and hasattr(handle, "block_until_ready"):
            try:
                handle.block_until_ready()
            except Exception as e:  # noqa: BLE001 — classified below
                msg = str(e).lower()
                if "donated" not in msg and "deleted" not in msg:
                    err = e
        return payload, err


def _u32(x):
    return jnp.asarray(x, jnp.uint32)


def choose_params(
    n_blocks: int, batch: int, *, R: int | None = None
) -> tuple[int, int]:
    """(R rows/partition, KMAX update-slots/fetch) for a filter/batch shape.

    Total MXU work scales with n_blocks*KMAX and per-partition overhead
    with n_blocks/R, so R balances the two (tuned on v5e); KMAX covers
    the Poisson(lambda = batch/P) occupancy out to ~8 sigma (the chunk
    loop correctness-covers anything beyond), is a multiple of 8 (DMA
    sublane tiling) and capped at 1024 — a VMEM bound only; exactness
    never depends on it (counts accumulate in f32, overflow goes to the
    chunk loop).
    """
    import math

    if R is None:
        # prefer per-partition occupancy (lambda) in ~[64, 256]: smaller
        # starves the MXU stages, larger inflates the KMAX^2 same-row
        # matmul (measured sweet spot on v5e)
        best = None
        for cand in (512, 1024):
            if cand > n_blocks or n_blocks % cand:
                continue
            lam = batch * cand // n_blocks
            score = abs(math.log2(max(lam, 1)) - 7)  # target lambda ~128
            if best is None or score < best[0]:
                best = (score, cand)
        R = best[1] if best else min(512, n_blocks)
    P = max(1, n_blocks // R)
    lam = max(1, batch // P)
    kmax = lam + max(16, int(8 * math.sqrt(lam)))
    kmax = min(1024, max(16, (kmax + 7) // 8 * 8))
    return R, kmax


def auto_insert_path(
    backend: str,
    n_blocks: int,
    batch: int,
    words_per_block: int = 16,
    *,
    presence: bool = False,
) -> str:
    """The implementation ``insert_path="auto"`` resolves to — the single
    source of truth shared by :func:`tpubloom.filter.make_blocked_insert_fn`
    and the benchmark's metadata. The Mosaic kernel only lowers on TPU;
    every other backend (cpu, gpu, ...) takes the XLA scatter path.
    ``presence`` must match the caller's fused-test-and-insert intent:
    the presence kernel has tighter caps, so the applicability decision
    and the kernel actually run must use the same predicate."""
    if backend == "tpu" and sweep_applicable(
        n_blocks, batch, words_per_block, presence=presence
    ):
        return "sweep"
    return "scatter"


def resolve_insert_path(
    config, batch: int, backend: str | None = None, *, presence: bool = False,
    n_blocks: int | None = None,
) -> str:
    """Resolve ``config.insert_path`` ("auto"/"sweep"/"scatter") for a
    batch size on the current (or given) backend. The ONE funnel for
    every insert-path decision (single-chip, presence, and — via the
    ``n_blocks`` override, which the sharded per-device hot loop uses to
    pass its LOCAL row count — the shard_map paths)."""
    if config.insert_path != "auto":
        return config.insert_path
    if backend is None:
        backend = jax.default_backend()
    return auto_insert_path(
        backend,
        config.n_blocks if n_blocks is None else n_blocks,
        batch,
        config.words_per_block,
        presence=presence,
    )


def sweep_applicable(
    n_blocks: int, batch: int, words_per_block: int = 16, *,
    presence: bool = False,
) -> bool:
    """The sweep wins when the array is large enough that partitions
    outnumber DMA latency and per-partition occupancy fits the fetch
    window; tiny filters / huge-batch-tiny-filter shapes stay on the
    sorted-scatter path."""
    if words_per_block + 2 > 128:
        # the update-stream row holds block id + W mask words + key idx
        # in 128 lanes; block_bits=4096 (W=128) does not fit
        return False
    if choose_fat_params(n_blocks, batch, words_per_block, presence=presence):
        return True
    R, kmax = choose_params(n_blocks, batch)
    P = max(1, n_blocks // R)
    if n_blocks % R != 0 or R % 32 != 0:
        return False
    if batch * R < 8 * n_blocks:
        # minimum per-partition occupancy (lambda >= 8): the sweep streams
        # the WHOLE block array HBM->VMEM->HBM per call, so a sparse batch
        # (e.g. a scalar insert into a 2^23-block filter) would pay the
        # full-array stream for a handful of rows — orders of magnitude
        # slower than the row scatter. Break-even on v5e is lambda ~1
        # (NB*128B / 819GB/s vs ~100ns/row scatter); 8 adds margin.
        return False
    # kmax covers lambda + 8 sigma by construction unless the 1024 cap
    # binds (tiny filter / huge batch), where the chunk loop would
    # serialize every partition
    return P >= 8 and batch // P < kmax


_ALIGN = 8  # Mosaic sublane tiling: DMA offsets/shapes on dim 0 in units of 8


def _kernel(
    starts_ref,  # SMEM [P+1] i32 (scalar prefetch)
    upd_ref,  # ANY [Btot, 128] u32: col 0 = block id, cols 1..W = mask words
    blocks_ref,  # VMEM [R, W] u32 (auto-streamed partition of the array)
    *rest,  # out_ref [, pres_ref], scratch sup_ref, sems
    R: int,
    KMAX: int,
    W: int,
    PRES: bool = False,
):
    if PRES:
        out_ref, pres_ref, sup_ref, sems = rest
    else:
        out_ref, sup_ref, sems = rest
        pres_ref = None
    p = pl.program_id(0)
    num_p = pl.num_programs(0)
    s0 = starts_ref[p]
    # DMA windows start at the 8-aligned floor of the partition start;
    # rows dragged in from the neighbour partition are inert (their
    # one-hot row match fails), so no count bookkeeping is needed.
    off0 = (s0 // _ALIGN) * _ALIGN
    end = starts_ref[p + 1]

    def fetch(slot, off):
        cp = pltpu.make_async_copy(
            upd_ref.at[pl.ds(off, KMAX), :], sup_ref.at[slot], sems.at[slot]
        )
        cp.start()
        return cp

    def wait(slot):
        pltpu.make_async_copy(
            upd_ref.at[pl.ds(0, KMAX), :], sup_ref.at[slot], sems.at[slot]
        ).wait()

    slot = lax.rem(p, 2)

    # chunk 0 of partition 0 has no predecessor to prefetch it
    @pl.when(p == 0)
    def _():
        fetch(0, off0)

    # prefetch chunk 0 of the NEXT partition into the other slot
    @pl.when(p + 1 < num_p)
    def _():
        fetch(1 - slot, (starts_ref[p + 1] // _ALIGN) * _ALIGN)

    wait(slot)

    col512 = lax.broadcasted_iota(jnp.int32, (KMAX, W * 32), 1)
    colsR = lax.broadcasted_iota(jnp.int32, (KMAX, R), 1)
    base = jnp.uint32(p * R)

    # pack weights: bit-plane column c = b*W + w contributes 2^(b mod 8)
    # to output column (b // 8) * W + w — the masks as 4W 8-bit
    # quarters. Quarter splitting keeps every packed value <= 255, which
    # is EXACT in bf16 — the MXU runs "f32" matmuls as bf16 passes, so
    # operands and results must stay in bf16's integer-exact range.
    ccol = lax.broadcasted_iota(jnp.int32, (W * 32, 4 * W), 0)
    hcol = lax.broadcasted_iota(jnp.int32, (W * 32, 4 * W), 1)
    b_of_c = ccol // W
    w_of_c = lax.rem(ccol, W)
    pack_w = jnp.where(
        (w_of_c + (b_of_c // 8) * W) == hcol,
        (1 << lax.rem(b_of_c, 8)).astype(jnp.float32),
        jnp.float32(0),
    ).astype(jnp.bfloat16)
    # combine weights: [4W, W] matrices folding quarter columns into
    # 16-bit half-words (q0 + 256*q1, and q2 + 256*q3) — both f32-exact
    # (<= 65535). Matmul-based because static lane slicing of the 4W
    # array miscompiles on Mosaic.
    qcol = lax.broadcasted_iota(jnp.int32, (4 * W, W), 0)
    wcol = lax.broadcasted_iota(jnp.int32, (4 * W, W), 1)
    q_of = qcol // W
    w_of = lax.rem(qcol, W)
    comb_lo = jnp.where(
        (w_of == wcol) & (q_of < 2),
        jnp.where(q_of == 0, jnp.float32(1), jnp.float32(256)),
        jnp.float32(0),
    ).astype(jnp.bfloat16)
    comb_hi = jnp.where(
        (w_of == wcol) & (q_of >= 2),
        jnp.where(q_of == 2, jnp.float32(1), jnp.float32(256)),
        jnp.float32(0),
    ).astype(jnp.bfloat16)

    def chunk_delta(slot, want_presence=False):
        """delta[R, W] u32 word-OR contribution of the update slice in
        `slot` (and, when asked, the pre-update membership of each slot).
        All heavy lifting happens in update space ([KMAX, *]); nothing
        here scales with R*W*32.

        MXU stages (all exact):
          same  = oh @ oh^T        0/1 same-row indicator   (bf16 x bf16)
          cnts  = same @ bits      per-slot merged bit counts
          lohi  = present @ pack_w merged masks as 16-bit halves, f32
          delta = sel_first^T @ lohi  one exact f32 row per touched block
        """
        buf = sup_ref[slot]  # [KMAX, 128] u32
        rl = (buf[:, 0:1] - base).astype(jnp.int32)  # [KMAX, 1]
        # one-hot row match; rows outside [0, R) (neighbour partitions,
        # sentinel tail) wrapped far out of range and match no column.
        # NB: selects stay in 32-bit lanes (f32) before converting to
        # bf16 — a 32-bit predicate selecting 16-bit values trips a
        # Mosaic relayout bug ("non-singleton dimension replicated").
        ohf = jnp.where(rl == colsR, jnp.float32(1), jnp.float32(0))
        oh = ohf.astype(jnp.bfloat16)  # [KMAX, R]
        m = buf[:, 1 : W + 1]  # [KMAX, W] mask words
        # bit-plane expansion, b-major layout: column c = b*W + w holds
        # bit b of word w -> replicate the W words 32x along lanes, then
        # shift each lane by c // W.
        rep = jnp.concatenate([m] * 32, axis=1)  # [KMAX, W*32]
        bits = (rep >> (col512 // W).astype(jnp.uint32)) & _u32(1)
        bitsf = bits.astype(jnp.int32).astype(jnp.float32).astype(jnp.bfloat16)
        # same-row indicator via the Kronecker split of the one-hot:
        # r = 32*hi + lo, so oh = oh_hi (x) oh_lo and
        # same = (oh_hi oh_hi^T) * (oh_lo oh_lo^T) elementwise — two
        # contractions of depth R/32 + 32 instead of one of depth R
        # (~10x less MXU work for the kernel's biggest matmul). Exact:
        # all operands 0/1; out-of-range rows miss the hi match.
        rl_hi = rl // 32
        rl_lo = rl - rl_hi * 32
        ohh = jnp.where(
            rl_hi == lax.broadcasted_iota(jnp.int32, (KMAX, R // 32), 1),
            jnp.float32(1), jnp.float32(0),
        ).astype(jnp.bfloat16)
        ohl = jnp.where(
            rl_lo == lax.broadcasted_iota(jnp.int32, (KMAX, 32), 1),
            jnp.float32(1), jnp.float32(0),
        ).astype(jnp.bfloat16)
        same_hi = lax.dot_general(
            ohh, ohh, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        same_lo = lax.dot_general(
            ohl, ohl, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        same = (same_hi * same_lo).astype(jnp.bfloat16)  # [KMAX, KMAX]
        cnts = lax.dot_general(
            same, bitsf, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [KMAX, W*32] per-slot group-merged bit counts
        present = jnp.where(cnts > 0, jnp.float32(1), jnp.float32(0)).astype(
            jnp.bfloat16
        )
        # select exactly one representative slot per row group: slot j
        # is "first" iff no earlier slot j' < j shares its row. Derived
        # from `same` with an iota mask (no sublane shifts — those
        # miscompile on Mosaic).
        jj = lax.broadcasted_iota(jnp.int32, (KMAX, KMAX), 0)
        kk = lax.broadcasted_iota(jnp.int32, (KMAX, KMAX), 1)
        earlier = jnp.where(kk < jj, same.astype(jnp.float32), jnp.float32(0))
        n_before = jnp.sum(earlier, axis=1, keepdims=True)  # [KMAX, 1]
        first = jnp.where(n_before == 0, jnp.float32(1), jnp.float32(0))
        ohsel = (ohf * first).astype(jnp.bfloat16)  # one 1 per touched row
        quarters = lax.dot_general(
            present, pack_w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [KMAX, 4W] merged masks as 8-bit quarters (bf16-exact)
        delta_q = lax.dot_general(
            ohsel, quarters.astype(jnp.bfloat16), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(jnp.bfloat16)  # [R, 4W] — exact: one weight-1 term per row
        lo = lax.dot_general(
            delta_q, comb_lo, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [R, W] f32-exact 16-bit lo halves
        hi = lax.dot_general(
            delta_q, comb_hi, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        delta = lo.astype(jnp.int32).astype(jnp.uint32) | (
            hi.astype(jnp.int32).astype(jnp.uint32) << _u32(16)
        )
        if not want_presence:
            return delta

        # -- pre-update membership of each slot (test-and-insert) ------
        # Extract each slot's OLD block row with the same one-hot matmul,
        # one 8-bit quarter at a time (bf16-exact <= 255), and test
        # (row & mask) == mask across all W words and 4 quarters.
        tile = blocks_ref[:]  # [R, W] u32, pre-update by construction
        acc_ok = None
        for q in range(4):
            tq_f = (
                ((tile >> _u32(8 * q)) & _u32(0xFF))
                .astype(jnp.int32)
                .astype(jnp.float32)
                .astype(jnp.bfloat16)
            )
            rq = lax.dot_general(
                oh, tq_f, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [KMAX, W] f32-exact quarter of the slot's old row
            rq_u = rq.astype(jnp.int32).astype(jnp.uint32)
            mq = (m >> _u32(8 * q)) & _u32(0xFF)
            ok = jnp.where((mq & rq_u) == mq, jnp.float32(1), jnp.float32(0))
            acc_ok = ok if acc_ok is None else acc_ok * ok
        # all W words must match; slots with no row in this partition
        # (oh all-zero -> row 0) produce garbage, masked by `real` below
        hit = jnp.min(acc_ok, axis=1, keepdims=True)  # [KMAX, 1] f32
        return delta, hit

    delta, hit0 = chunk_delta(slot, want_presence=True) if PRES else (
        chunk_delta(slot), None
    )

    # overflow chunks (adversarial skew only): serial fetch + word-OR.
    # Groups spanning a chunk boundary contribute one partial merge per
    # chunk; OR-accumulating packed words keeps that exact. (Presence is
    # emitted for chunk-0 windows only; the host falls back to a gather
    # query for batches where any partition overflows.)
    nch = (end - off0 + (KMAX - 1)) // KMAX

    def body(c, acc):
        fetch(slot, off0 + c * KMAX).wait()
        return acc | chunk_delta(slot)

    delta = lax.fori_loop(1, nch, body, delta)

    if PRES:
        # Pack (idx+1 | hit<<31) per slot into an [8, KMAX/8] tile, slot
        # j at (j % 8, j // 8). The sublane->lane move is done with four
        # exact byte matmuls ((oh_a * v_byte)^T @ oh_b) because Mosaic
        # supports neither the reshape nor sublane shifts.
        buf = sup_ref[slot]
        idxp1 = buf[:, W + 1 : W + 2]  # [KMAX, 1] u32, idx+1 (0 = filler)
        ipos = lax.broadcasted_iota(jnp.int32, (KMAX, 1), 0) + off0
        real = (ipos >= s0) & (ipos < end) & (idxp1 > 0)
        hbit = jnp.where(hit0 > 0.5, _u32(0x80000000), _u32(0))
        v = jnp.where(real, idxp1 | hbit, _u32(0))  # [KMAX, 1]
        jj8 = lax.broadcasted_iota(jnp.int32, (KMAX, 8), 0)
        aa8 = lax.broadcasted_iota(jnp.int32, (KMAX, 8), 1)
        oh_a = jnp.where(jj8 % 8 == aa8, jnp.float32(1), jnp.float32(0))
        jjc = lax.broadcasted_iota(jnp.int32, (KMAX, KMAX // 8), 0)
        ccc = lax.broadcasted_iota(jnp.int32, (KMAX, KMAX // 8), 1)
        oh_b = jnp.where(jjc // 8 == ccc, jnp.float32(1), jnp.float32(0)).astype(
            jnp.bfloat16
        )
        pres = jnp.zeros((8, KMAX // 8), jnp.uint32)
        for q in range(4):
            vb = (
                ((v >> _u32(8 * q)) & _u32(0xFF))
                .astype(jnp.int32)
                .astype(jnp.float32)
            )
            left = (oh_a * vb).astype(jnp.bfloat16)  # [KMAX, 8]
            outq = lax.dot_general(
                left, oh_b, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [8, KMAX//8] f32-exact bytes
            pres = pres | (
                outq.astype(jnp.int32).astype(jnp.uint32) << _u32(8 * q)
            )
        pres_ref[:] = pres

    out_ref[:] = blocks_ref[:] | delta


def sweep_insert(
    blocks: jnp.ndarray,
    updates: jnp.ndarray,
    starts: jnp.ndarray,
    *,
    R: int,
    KMAX: int,
    interpret: bool = False,
    with_presence: bool = False,
):
    """Apply sorted (block, mask) updates to ``blocks`` via the sweep kernel.

    Args:
      blocks: ``uint32[NB, W]``.
      updates: ``uint32[Btot, 128]`` sorted update stream: column 0 is the
        block id (ascending; padding/sentinel rows hold ``NB`` and sit at
        the tail), columns ``1..W`` the mask words, column ``W+1`` the
        original key index + 1 when ``with_presence`` (0 = filler), the
        rest zero. The 128-lane row keeps every DMA slice tile-aligned.
        ``Btot`` must include ``>= KMAX + 8`` rows of tail padding so
        chunk DMA windows stay in bounds.
      starts: ``int32[P+1]`` partition boundaries
        (``starts[p]`` = first index with ``block id >= p*R``).

    Returns ``new_blocks``, or ``(new_blocks, pres)`` when
    ``with_presence``: ``pres`` is ``uint32[P*8, KMAX//8]`` holding
    ``idx+1 | was_present << 31`` per update slot (slot j of partition p
    at ``[p*8 + j % 8, j // 8]``; 0 = no slot). Presence is relative to
    the PRE-batch array and only valid when no partition overflowed its
    chunk-0 window (callers check and fall back).
    """
    NB, W = blocks.shape
    P = NB // R
    out_shape = jax.ShapeDtypeStruct((NB, W), jnp.uint32)
    out_spec = pl.BlockSpec((R, W), lambda p, *_: (p, 0))
    if with_presence:
        out_shape = (
            out_shape,
            jax.ShapeDtypeStruct((P * 8, KMAX // 8), jnp.uint32),
        )
        out_spec = (out_spec, pl.BlockSpec((8, KMAX // 8), lambda p, *_: (p, 0)))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(P,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((R, W), lambda p, *_: (p, 0)),
        ],
        out_specs=out_spec,
        scratch_shapes=[
            pltpu.VMEM((2, KMAX, 128), jnp.uint32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    fn = pl.pallas_call(
        functools.partial(_kernel, R=R, KMAX=KMAX, W=W, PRES=with_presence),
        out_shape=out_shape,
        grid_spec=grid_spec,
        input_output_aliases={2: 0},
        interpret=interpret,
    )
    return fn(starts, updates, blocks)


def _stream_scaffold(bs, nb: int, P: int, R: int, KMAX: int):
    """Shared host-side sweep-stream assembly: partition boundaries from
    the sorted block ids, plus the padded 128-lane update buffer with
    column 0 = block id (sentinel ``nb`` rows in the tail slack so every
    8-aligned chunk DMA window stays in bounds). Callers fill their
    payload columns into the returned buffer."""
    B = bs.shape[0]
    starts = jnp.searchsorted(
        bs, (jnp.arange(P + 1, dtype=jnp.int32) * R).astype(jnp.int32)
    ).astype(jnp.int32)
    pad = KMAX + _ALIGN
    upd = jnp.zeros((B + pad, 128), jnp.uint32)
    upd = upd.at[:, 0].set(
        jnp.concatenate([bs.astype(jnp.uint32), jnp.full((pad,), nb, jnp.uint32)])
    )
    return starts, upd


def _count_kernel(
    starts_ref,  # SMEM [P+1] i32 (scalar prefetch)
    upd_ref,  # ANY [Btot, 128] u32: col 0 = block id, cols 1..W = nibble counts
    blocks_ref,  # VMEM [R, W] u32 (auto-streamed partition of the counters)
    out_ref,  # VMEM [R, W] u32
    sup_ref,  # VMEM scratch [2, KMAX, 128] u32
    sems,  # DMA sems [2]
    *,
    R: int,
    KMAX: int,
    W: int,
    INCREMENT: bool,
):
    """Blocked-counting partition sweep: saturating nibble add/subtract.

    Per update slot the stream carries the key's per-counter multiplicity
    pre-packed as 4-bit nibbles in W words — the SAME (word, nibble)
    layout as the counter storage itself, so one concat-and-shift
    unpacks either side. Counts are additive, so no same-row merge or
    representative selection is needed: counts[R, 128 planes] is one
    exact one-hot matmul, accumulated over overflow chunks (clamped at
    16 per chunk — already saturating/flooring, and it keeps every f32
    sum exact under adversarial duplicate skew). The tile is fully
    rewritten with min(15, old + cnt) (insert) / max(0, old - cnt)
    (delete) — identical one-clamp semantics to ops.counting
    (cpu_ref._counter_add ground truth).
    """
    p = pl.program_id(0)
    num_p = pl.num_programs(0)
    s0 = starts_ref[p]
    off0 = (s0 // _ALIGN) * _ALIGN
    end = starts_ref[p + 1]

    def fetch(slot, off):
        cp = pltpu.make_async_copy(
            upd_ref.at[pl.ds(off, KMAX), :], sup_ref.at[slot], sems.at[slot]
        )
        cp.start()
        return cp

    def wait(slot):
        pltpu.make_async_copy(
            upd_ref.at[pl.ds(0, KMAX), :], sup_ref.at[slot], sems.at[slot]
        ).wait()

    slot = lax.rem(p, 2)

    @pl.when(p == 0)
    def _():
        fetch(0, off0)

    @pl.when(p + 1 < num_p)
    def _():
        fetch(1 - slot, (starts_ref[p + 1] // _ALIGN) * _ALIGN)

    wait(slot)

    CPB = W * 8  # counters per block = nibble planes
    colC = lax.broadcasted_iota(jnp.int32, (KMAX, CPB), 1)
    colsR = lax.broadcasted_iota(jnp.int32, (KMAX, R), 1)
    base = jnp.uint32(p * R)

    def chunk_counts(slot):
        """Clamped per-(row, plane) multiplicities from the slot buffers."""
        buf = sup_ref[slot]  # [KMAX, 128] u32
        rl = (buf[:, 0:1] - base).astype(jnp.int32)
        ohf = jnp.where(rl == colsR, jnp.float32(1), jnp.float32(0))
        oh = ohf.astype(jnp.bfloat16)  # [KMAX, R]
        m = buf[:, 1 : W + 1]  # [KMAX, W] packed 4-bit multiplicities
        # plane c = (nibble c // W) of word (c mod W) — concat W-wide
        # copies, shift each lane by 4 * (c // W)
        rep = jnp.concatenate([m] * 8, axis=1)  # [KMAX, CPB]
        nib = (rep >> ((colC // W).astype(jnp.uint32) * _u32(4))) & _u32(15)
        nibf = nib.astype(jnp.int32).astype(jnp.float32).astype(jnp.bfloat16)
        cnts = lax.dot_general(
            oh, nibf, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [R, CPB], exact (<= 15 * KMAX < 2^24)
        return jnp.minimum(cnts, jnp.float32(16))

    acc = chunk_counts(slot)
    nch = (end - off0 + (KMAX - 1)) // KMAX

    def body(c, a):
        fetch(slot, off0 + c * KMAX).wait()
        return a + chunk_counts(slot)

    acc = lax.fori_loop(1, nch, body, acc)

    # old counters, same plane layout
    tile = blocks_ref[:]
    trep = jnp.concatenate([tile] * 8, axis=1)  # [R, CPB]
    tcolC = lax.broadcasted_iota(jnp.int32, (R, CPB), 1)
    old = (trep >> ((tcolC // W).astype(jnp.uint32) * _u32(4))) & _u32(15)
    oldf = old.astype(jnp.int32).astype(jnp.float32)
    if INCREMENT:
        new = jnp.minimum(oldf + acc, jnp.float32(15))
    else:
        new = jnp.maximum(oldf - acc, jnp.float32(0))
    newb = new.astype(jnp.bfloat16)  # <= 15, bf16-exact

    # pack planes back into words: byte q of word w = plane(2q, w) +
    # 16 * plane(2q+1, w); four separate matmuls (no lane slicing)
    pc = lax.broadcasted_iota(jnp.int32, (CPB, W), 0)
    pw = lax.broadcasted_iota(jnp.int32, (CPB, W), 1)
    n_of = pc // W
    w_of = lax.rem(pc, W)
    packed = jnp.zeros((R, W), jnp.uint32)
    for q in range(4):
        wq = jnp.where(
            (w_of == pw) & (n_of // 2 == q),
            jnp.where(lax.rem(n_of, 2) == 0, jnp.float32(1), jnp.float32(16)),
            jnp.float32(0),
        ).astype(jnp.bfloat16)
        byte = lax.dot_general(
            newb, wq, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [R, W] f32-exact bytes
        packed = packed | (
            byte.astype(jnp.int32).astype(jnp.uint32) << _u32(8 * q)
        )
    out_ref[:] = packed


def sweep_counter_update(
    blocks: jnp.ndarray,
    updates: jnp.ndarray,
    starts: jnp.ndarray,
    *,
    R: int,
    KMAX: int,
    increment: bool,
    interpret: bool = False,
) -> jnp.ndarray:
    """Apply sorted per-block nibble-count updates to the packed counters."""
    NB, W = blocks.shape
    P = NB // R
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(P,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((R, W), lambda p, *_: (p, 0)),
        ],
        out_specs=pl.BlockSpec((R, W), lambda p, *_: (p, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, KMAX, 128), jnp.uint32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    fn = pl.pallas_call(
        functools.partial(
            _count_kernel, R=R, KMAX=KMAX, W=W, INCREMENT=increment
        ),
        out_shape=jax.ShapeDtypeStruct((NB, W), jnp.uint32),
        grid_spec=grid_spec,
        input_output_aliases={2: 0},
        interpret=interpret,
    )
    return fn(starts, updates, blocks)


def apply_counter_updates(
    blocks: jnp.ndarray,
    blk: jnp.ndarray,
    cpos: jnp.ndarray,
    valid: jnp.ndarray,
    *,
    counters_per_block: int,
    k: int,
    increment: bool,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Apply each valid key's blocked-counting update to ``blocks`` via the
    counting sweep (saturating +1 / flooring -1 per counter occurrence).

    The kernel-facing entry point shared by the single-chip path and the
    sharded per-device path (which routes keys first and passes
    device-local row ids). ``blk int32[B]`` block rows, ``cpos
    uint32[B, k]`` in-block counter positions, ``valid bool[B]``; invalid
    keys are dropped. Requires ``k <= 15`` (per-key multiplicity must fit
    the 4-bit stream nibbles).
    """
    nb, w = blocks.shape
    B = blk.shape[0]
    cpb = counters_per_block
    R, KMAX = choose_params(nb, B)
    if nb % R != 0 or w + 1 > 128:
        raise ValueError(
            f"sweep counter update does not support this shape "
            f"(n_blocks={nb}, R={R}, words_per_block={w})"
        )
    P = nb // R
    interp = jax.default_backend() == "cpu" if interpret is None else interpret
    blk = jnp.where(valid, blk, nb)
    cols, nbits, packed = _pack_positions(cpos, cpb, k)
    sorted_cols = lax.sort((blk,) + cols, num_keys=1)
    bs = sorted_cols[0]
    cpos_s = _unpack_positions(sorted_cols[1:], cpb, k, nbits, packed)
    # per-key multiplicity of each counter, packed 4 bits per nibble
    # in the counter-storage (word, nibble) layout: counter c lives
    # in word c >> 3, nibble c & 7 — multiplicity <= k <= 15
    planes = jnp.zeros((B, cpb), jnp.uint32)
    iota_c = lax.broadcasted_iota(jnp.uint32, (B, cpb), 1)
    for i in range(k):
        planes = planes + (cpos_s[:, i : i + 1] == iota_c).astype(jnp.uint32)
    pw = planes.reshape(B, w, 8)
    shifts = (jnp.arange(8, dtype=jnp.uint32) * 4)[None, None, :]
    cnt_words = jnp.sum(pw << shifts, axis=2, dtype=jnp.uint32)  # [B, W]
    starts, upd = _stream_scaffold(bs, nb, P, R, KMAX)
    upd = upd.at[:B, 1 : w + 1].set(cnt_words)
    return sweep_counter_update(
        blocks, upd, starts,
        R=R, KMAX=KMAX, increment=increment, interpret=interp,
    )


def make_sweep_counter_fn(
    config, *, increment: bool, interpret: bool | None = None,
    storage_fat: bool = False,
):
    """Pure ``(blocks[NB,W], keys_u8, lengths) -> blocks`` blocked-counting
    update (insert = saturating +1 per counter occurrence, delete =
    flooring -1) via the partition sweep. Bit-identical to the flat
    counting kernel applied at positions ``blk * counters_per_block + c``
    (tpubloom.filter.make_blocked_counter_fn's fallback path).

    Prefers the fat-row counting kernel when the shape qualifies (the
    128-lane DMA tier — benchmarks/RESULTS_r3.md §2); the legacy
    [NB, W]-tile kernel is the fallback. ``storage_fat``: blocks are the
    fat [NB/J, 128] view in and out.
    """
    nb, cpb, w = config.n_blocks, config.counters_per_block, config.words_per_block
    k, seed, bh = config.k, config.seed, config.block_hash

    def update(blocks, keys_u8, lengths):
        valid = lengths >= 0
        blk, cpos = blocked.block_positions(
            keys_u8, jnp.maximum(lengths, 0),
            n_blocks=nb, block_bits=cpb, k=k, seed=seed, block_hash=bh,
        )
        fat = choose_fat_params(nb, keys_u8.shape[0], w, counting=True)
        if fat is not None:
            return apply_fat_counter_updates(
                blocks, blk, cpos, valid,
                counters_per_block=cpb, k=k, increment=increment,
                params=fat, interpret=interpret, storage_fat=storage_fat,
            )
        out = apply_counter_updates(
            blocks.reshape(nb, w) if storage_fat else blocks,
            blk, cpos, valid,
            counters_per_block=cpb, k=k, increment=increment,
            interpret=interpret,
        )
        return out.reshape(blocks.shape) if storage_fat else out

    return update


def _pack_positions(bit: jnp.ndarray, block_bits: int, k: int):
    """Pack ``uint32[B, k]`` in-block positions into few u32 payload columns
    for the sort (9 bits each at block_bits=512). Returns
    ``(cols, nbits, packed)``; when ``k*log2(bb) > 64`` the positions ride
    the sort as one column each (``packed=False``). The explicit flag —
    not ``len(cols)`` — tells unpack which form it got (k=2 would be
    ambiguous otherwise)."""
    nbits = max(1, (block_bits - 1).bit_length())
    if k * nbits <= 64:
        lo = jnp.zeros(bit.shape[:-1], jnp.uint32)
        hi = jnp.zeros(bit.shape[:-1], jnp.uint32)
        for i in range(k):
            sh = i * nbits
            if sh < 32:
                lo = lo | (bit[..., i] << _u32(sh))
                if sh + nbits > 32:
                    hi = hi | (bit[..., i] >> _u32(32 - sh))
            else:
                hi = hi | (bit[..., i] << _u32(sh - 32))
        return (lo, hi), nbits, True
    return tuple(bit[..., i] for i in range(k)), nbits, False


def _unpack_positions(cols, block_bits: int, k: int, nbits: int, packed: bool):
    if not packed:
        return jnp.stack(cols, axis=-1)
    lo, hi = cols
    mask = _u32(block_bits - 1)
    outs = []
    for i in range(k):
        sh = i * nbits
        if sh < 32:
            v = lo >> _u32(sh)
            if sh + nbits > 32:
                v = v | (hi << _u32(32 - sh))
        else:
            v = hi >> _u32(sh - 32)
        outs.append(v & mask)
    return jnp.stack(outs, axis=-1)


def apply_blocked_updates(
    blocks: jnp.ndarray,
    blk: jnp.ndarray,
    bit: jnp.ndarray,
    valid: jnp.ndarray,
    *,
    block_bits: int,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """OR each valid key's blocked-spec bits into ``blocks`` via the sweep.

    The kernel-facing entry point shared by the single-chip path and the
    sharded per-device path (which routes keys first and passes
    device-local row ids). ``blk int32[B]``, ``bit uint32[B, k]``
    (in-block positions), ``valid bool[B]``; invalid keys are dropped.
    """
    nb, w = blocks.shape
    B = blk.shape[0]
    k = bit.shape[-1]
    fat = choose_fat_params(nb, B, w)
    if fat is not None:
        return apply_fat_updates(
            blocks, blk, bit, valid,
            block_bits=block_bits, params=fat, interpret=interpret,
        )
    R, KMAX = choose_params(nb, B)
    if nb % R != 0 or w + 2 > 128 or R % 32 != 0:
        # R must be a multiple of 32 for the Kronecker one-hot split
        # (rows beyond 32*(R//32) would silently drop their inserts)
        raise ValueError(
            f"sweep insert does not support this shape (n_blocks={nb}, "
            f"R={R}, words_per_block={w}) — use insert_path='scatter'"
        )
    P = nb // R
    interp = jax.default_backend() == "cpu" if interpret is None else interpret
    blk = jnp.where(valid, blk, nb)
    cols, nbits, packed = _pack_positions(bit, block_bits, k)
    sorted_cols = lax.sort((blk,) + cols, num_keys=1)
    bs = sorted_cols[0]
    bit_sorted = _unpack_positions(sorted_cols[1:], block_bits, k, nbits, packed)
    masks = blocked.build_masks(bit_sorted, w)
    starts, upd = _stream_scaffold(bs, nb, P, R, KMAX)
    upd = upd.at[:B, 1 : w + 1].set(masks)
    return sweep_insert(blocks, upd, starts, R=R, KMAX=KMAX, interpret=interp)


# =========================================================================
# Fat-row (128-lane) partition sweep — "sweep3", the shipping TPU hot loop
# =========================================================================
#
# Why a second kernel generation: benchmarks/hbm_probe.py measured that
# this chip's Pallas DMA moves [*, W=16]-lane tiles at ~35 GB/s but
# [*, 128]-lane tiles at ~150-190 GB/s (the (8, 128) DMA tiling wastes
# 8x on narrow tiles), so the original per-block-row pipeline above was
# bandwidth-crippled by its own layout. A [NB, W] u32 block array is the
# SAME row-major memory as [NB/J, 128] with J = 128/W blocks per fat
# row, so the fat sweep:
#
# * sorts keys by skey = (blk mod J) * NBJ + (blk div J): J substreams,
#   one per block-column j; substream j's updates touch only lanes
#   [j*W, (j+1)*W) of the fat rows, so each substream's delta is
#   produced independently and lane-concatenated — no sublane<->lane
#   moves anywhere;
# * runs the placement one-hot over FAT rows (R8 per sub-tile), so the
#   cnt matmul is J-times narrower per window at equal coverage — the
#   int8 MXU does NB*bb*KJ MACs/pass with KJ ~ lambda+8sigma per
#   (j, window);
# * computes fused test-and-insert presence with ONE extra int8 matmul
#   per window (G = mask_bits @ oldrow_bits^T; slot hits iff
#   G[s, row(s)] == popcount(mask_s)) instead of per-slot extraction.
#
# Measured on the same chip / same stream (B=4M, m=2^32, k=7, bb=512,
# to-value timing): insert-only 31-34 ms (124-135M keys/s) vs 77 ms for
# the legacy kernel; fused test-and-insert 70 ms (60M keys/s) vs 115 ms.
# Results are bit-identical to the legacy kernel and the XLA scatter
# path (same blocked position spec).


# Device generations whose fat-kernel caps below are hardware-measured
# (benchmarks/out/presence_geom_r5.json, adversarial_r5.json,
# geom8m_r5.json). On any OTHER TPU generation every geometry is
# probe-compiled; on v5e itself, presence/counting geometries OUTSIDE
# the validated set below are probed too — round 5 measured that
# Mosaic's scoped-VMEM acceptance is NOT a clean function of the
# (bodies, volume) caps ((256,2,KJP=176) fails at 2.88M "volume" while
# (512,2,KJP=96) passes at 3.15M), so the caps prune the search and
# the probe is the ground truth for unlisted corners. A failed probe
# demotes to the next candidate shape / scatter path instead of
# erroring at first use.
_VALIDATED_DEVICE_KINDS = ("TPU v5 lite",)
_GEOM_PROBE_CACHE: dict = {}
#: per-device-kind PERSISTENT probe results (ISSUE 11 satellite, ADVICE
#: r5 #4): a cold start on an unvalidated TPU generation used to pay
#: ~60 s of speculative Mosaic compiles — and every rolling restart of
#: a fleet pays it again. Successful probes are written through to
#: ``$TPUBLOOM_CACHE_DIR`` (default ``~/.cache/tpubloom``), keyed by
#: device kind, so the second process start performs ZERO speculative
#: probe compiles. Only ``ok=True`` results persist: a cached FAILURE
#: would outlive the transient compile-service errors the in-process
#: retry exists for, silently demoting every future process — a restart
#: must stay the documented re-probe escape hatch.
_GEOM_DISK_CACHE: dict = {}  # device kind -> set of ok key strings
_GEOM_DISK_LOADED: set = set()  # device kinds whose file was read
# (J, R8, S, KJP) tuples that compiled AND ran bit-exact on v5e
# hardware this round (adversarial_r5.json, presence_geom_r5.json,
# kj_slack_r5.json, geom8m_r5.json, bench/b_sweep runs).
_VALIDATED_GEOMS = {
    "presence": {
        (8, 512, 2, 96),    # B=4M shipping (KJ=352)
        (8, 512, 2, 104),   # B=4M/8M at 8-sigma (KJ=384)
        (8, 256, 2, 96),    # B=8M 6-sigma (KJ=352)
        (8, 256, 2, 104),   # B=8M 8-sigma (KJ=384)
        (8, 512, 1, 176),   # B=8M lambda=512 (KJ=648)
        (8, 1024, 1, 64),   # B=1M lambda=128 at R8=1024 (KJ=200)
        (8, 256, 4, 64),    # presence_geom (KJ=224)
        (8, 128, 4, 96),    # m=2^28 adversarial (KJ=352)
        (8, 128, 4, 64),    # small-filter corners (KJ<=224)
        (16, 512, 1, 64),   # bb=256 adversarial (KJ=200)
        (4, 256, 4, 352),   # bb=1024 pack=1 adversarial (KJ=352)
    },
    "counting": {
        (8, 256, 4, 64),    # config-4 B=4M (KJ=224)
        (8, 128, 4, 64),    # B=8M lambda=128 (73.2M ops/s)
        (8, 256, 2, 104),   # B=8M lambda=256 (74.0M — geom_ins_r5.json)
    },
}


def _probe_env():
    """Device kind when probe compiles apply (TPU backend), else None.
    The one seam between the probe machinery and the hardware — tests
    monkeypatch it to exercise the cache off-TPU."""
    try:
        if jax.default_backend() != "tpu":
            return None
        return jax.devices()[0].device_kind
    except Exception:
        return None


def _geom_cache_path(kind: str) -> str:
    import os
    import re

    base = os.environ.get("TPUBLOOM_CACHE_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "tpubloom"
    )
    slug = re.sub(r"[^A-Za-z0-9._-]+", "-", kind)
    return os.path.join(base, f"geomprobe-{slug}.json")


def _geom_cache_salt() -> str:
    """Version salt invalidating the persisted probe results: a stale
    ok=True surviving a kernel-code or jax/Mosaic upgrade would skip
    the probe for a geometry that no longer compiles — converting
    graceful demotion into a hard runtime failure at first real use.
    Upgrades cost one re-probe pass instead."""
    from tpubloom import version

    return f"{version.__version__}|jax-{jax.__version__}"


def _geom_disk_get(kind: str, key_str: str) -> bool:
    """True when a previous PROCESS probed this geometry ok on this
    device kind AT THIS CODE VERSION (best-effort: any read problem —
    missing file, torn JSON, CRC mismatch, salt mismatch — reads as a
    miss)."""
    if kind not in _GEOM_DISK_LOADED:
        _GEOM_DISK_LOADED.add(kind)
        from tpubloom.utils import crcjson

        payload = crcjson.load(_geom_cache_path(kind), ("geoms", "salt"))
        geoms = payload.get("geoms") if payload else None
        if payload is None or payload.get("salt") != _geom_cache_salt():
            geoms = None
        _GEOM_DISK_CACHE[kind] = set(
            geoms if isinstance(geoms, list) else ()
        )
    return key_str in _GEOM_DISK_CACHE.get(kind, ())


def _geom_disk_put(kind: str, key_str: str) -> None:
    """Write-through one ok probe result. Multi-process safe for the
    fleet-rolling-restart case the cache exists for: the file is
    RE-READ and unioned before each write (a sibling process's probes
    landed between our load and now must not be clobbered), and the
    write goes through a pid-unique path + ``os.replace`` so two
    concurrent writers cannot tear each other's tmp file. Best-effort
    throughout — a read-only cache dir must not break the hot path."""
    import os

    from tpubloom.utils import crcjson

    _GEOM_DISK_CACHE.setdefault(kind, set()).add(key_str)
    path = _geom_cache_path(kind)
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        merged = set(_GEOM_DISK_CACHE[kind])
        current = crcjson.load(path, ("geoms", "salt"))
        if current and current.get("salt") == _geom_cache_salt():
            geoms = current.get("geoms")
            if isinstance(geoms, list):
                merged.update(geoms)
        _GEOM_DISK_CACHE[kind] = merged
        mine = f"{path}.{os.getpid()}"
        crcjson.store(mine, {
            "geoms": sorted(merged),
            "salt": _geom_cache_salt(),
        })
        os.replace(mine, path)
    except OSError:
        pass


def _probe_compile(fn, blocks_sds, upd_sds, starts_sds):
    """One speculative Mosaic AOT compile (counted in
    ``geometry_probe_compiles``), attempted TWICE before reporting
    failure: this environment's compile service surfaces transient
    failures (dropped connections, HTTP 500) as generic exceptions,
    indistinguishable from a real Mosaic limit — and a cached False
    silently demotes the process to slower shapes/scatter for its
    lifetime (ADVICE r5 #2; bench.py retries the same failure mode). A
    real scoped-VMEM OOM fails both attempts. Returns ``(ok, exc)``."""
    from tpubloom.obs import counters as obs_counters

    obs_counters.incr("geometry_probe_compiles")
    ok, last_exc = False, None
    for _attempt in range(2):
        try:
            jax.jit(fn).lower(blocks_sds, upd_sds, starts_sds).compile()
            ok = True
            break
        except Exception as e:  # noqa: BLE001 — any compile failure demotes
            last_exc = e
    return ok, last_exc


_VALIDATED_KBJP_CAPS: dict = {}


def _validated_kbjp_cap(kind_name: str, sig) -> int:
    """Largest packed big-fetch row count (kbjp) any chooser-reachable
    lambda can pair with this validated (J, R8, S, KJP) signature —
    ADVICE r5 #3: the window-fetch scratch ``2*J*kbjp*128*4`` is part
    of the hardware-validated footprint, so a geometry whose kbjp
    exceeds what the signature pins must probe instead of riding the
    fast path. Derived by inverting the chooser's KJ(lambda) step
    function (slack 6 for presence, 8 otherwise) over the feasible
    lambda range; memoized — ~2k-iteration integer scan, once per
    signature per process."""
    cached = _VALIDATED_KBJP_CAPS.get((kind_name, sig))
    if cached is not None:
        return cached
    import math

    J, R8, S, KJP = sig
    w = 128 // J
    presence = kind_name == "presence"
    pk = fat_pack(w, presence)
    slack = 6 if presence else 8
    cap = 0
    for lam in range(8, 2049):
        kj = max(16, (lam + max(16, int(slack * math.sqrt(lam))) + 7) // 8 * 8)
        if kj > 1024 or _packed_rows(kj, pk) != KJP:
            continue
        kbj = ((lam * S + kj + 64 + 7) // 8) * 8
        cap = max(cap, _packed_rows(kbj, pk))
    _VALIDATED_KBJP_CAPS[(kind_name, sig)] = cap
    return cap


def _fat_geometry_compiles(
    nb: int, w: int, geom, *, presence: bool, counting: bool,
    query: bool = False, batch: int | None = None,
) -> bool:
    """True if the fat kernel at ``geom`` compiles on the current device.

    On v5e, insert geometries inside the caps always pass (no insert
    OOM was ever measured inside them), and presence/counting
    geometries pass if listed in ``_VALIDATED_GEOMS`` with a big-fetch
    footprint the signature pins (:func:`_validated_kbjp_cap`); anything
    else — and everything on other TPU generations — is lowered +
    compiled AOT against ShapeDtypeStructs (no operand allocation) in a
    try/except. With ``batch`` the probe's update buffer carries the
    REAL runtime row count (ADVICE r5 #1 — the compile is then
    shape-identical to the first real call, so a passing probe cannot
    hide an operand-extent-dependent failure); results are cached per
    process AND per device kind on disk (ok only — see the
    ``_GEOM_DISK_CACHE`` note). CPU/GPU backends return True unchanged:
    the sweep path is never auto-selected off-TPU, and tests drive the
    kernel in interpret mode where Mosaic limits don't apply."""
    kind = _probe_env()
    if kind is None:
        return True
    J, R8, S, KJ, KBJ = geom
    # pack must match the kernel the runtime will launch: both the
    # chooser's volume bound and apply_fat_counter_updates use
    # fat_pack(w, presence) — probing a pack=1 counting kernel would
    # validate a strictly lighter scoped-VMEM footprint than the real
    # PACK=4 unroll. The query kernel's stream carries the idx column
    # like presence streams, so its pack matches presence's.
    pk = fat_pack(w, presence or query)
    kbjp = _packed_rows(KBJ, pk)
    if any(v in kind for v in _VALIDATED_DEVICE_KINDS):
        if not (presence or counting or query):
            return True
        if not query:
            kname = "presence" if presence else "counting"
            sig = (J, R8, S, _packed_rows(KJ, pk))
            if sig in _VALIDATED_GEOMS[kname] and kbjp <= _validated_kbjp_cap(
                kname, sig
            ):
                return True
        # query geometries have NO hardware-validated signature set yet
        # (ISSUE 12 ships the kernel; the first TPU round will grow one)
        # — every query shape probe-compiles, on v5e too, and the result
        # persists in the on-disk cache like any other probe.
    # update-stream rows exactly as _fat_stream will build them at
    # runtime; probes with no batch at hand keep the legacy stand-in
    if batch is None:
        upd_rows = kbjp + 16
    elif pk == 1:
        upd_rows = int(batch) + KBJ + _ALIGN
    else:
        upd_rows = -(-int(batch) // pk) + kbjp + _ALIGN
    key = (kind, nb, w, J, R8, S, KJ, KBJ, presence, counting, query, upd_rows)
    hit = _GEOM_PROBE_CACHE.get(key)
    if hit is not None:
        return hit
    key_str = "/".join(map(str, key[1:]))  # kind is the file, not the key
    if _geom_disk_get(kind, key_str):
        _GEOM_PROBE_CACHE[key] = True
        return True
    NBJ = nb // J
    blocks_sds = jax.ShapeDtypeStruct((NBJ, 128), jnp.uint32)
    upd_sds = jax.ShapeDtypeStruct((upd_rows, 128), jnp.uint32)
    starts_sds = jax.ShapeDtypeStruct((J * (NBJ // R8) + 1,), jnp.int32)
    if counting:
        fn = functools.partial(
            fat_sweep_counter, J=J, R8=R8, S=S, KJ=KJ, KBJ=KBJ, W=w,
            increment=True, pack=pk,
        )
    elif query:
        fn = functools.partial(
            fat_sweep_query, J=J, R8=R8, S=S, KJ=KJ, KBJ=KBJ, W=w, pack=pk,
        )
    else:
        fn = functools.partial(
            fat_sweep_insert, J=J, R8=R8, S=S, KJ=KJ, KBJ=KBJ, W=w,
            with_presence=presence, pack=pk,
        )
    ok, last_exc = _probe_compile(fn, blocks_sds, upd_sds, starts_sds)
    if not ok:
        import warnings

        from tpubloom.obs import counters as obs_counters

        # visible in /metrics as tpubloom_geometry_probe_demotions_total
        # — a nonzero value on a TPU host says the process is running
        # demoted and a restart/investigation is warranted
        obs_counters.incr("geometry_probe_demotions")
        warnings.warn(
            f"tpubloom: fat-sweep geometry {geom} failed its probe "
            f"compile twice on device kind {kind!r}; this geometry is "
            f"disabled for the process (falling back to the next "
            f"shape / scatter path). NOTE: the probe cannot tell a "
            f"real Mosaic limit from a persistent compile-service "
            f"error — restart the process to re-probe (failures are "
            f"deliberately NOT written to the on-disk probe cache). "
            f"Cause: {str(last_exc)[:300]}",
            RuntimeWarning,
            stacklevel=2,
        )
    _GEOM_PROBE_CACHE[key] = ok
    if ok:
        _geom_disk_put(kind, key_str)
    return ok


def choose_fat_params(
    nb: int, batch: int, words_per_block: int = 16, *, presence: bool = False,
    counting: bool = False,
):
    """(J, R8, S, KJ, KBJ) for the fat sweep, or None if the shape does
    not qualify (callers fall back to the legacy kernel / scatter).

    J = blocks per 128-lane fat row; R8 = fat rows per placement
    sub-tile; S = sub-tiles per grid step (DMA granularity); KJ = update
    slots per (substream, sub-tile) window (lambda + slack, multiple of
    8 — 6 sigma for presence, 8 sigma otherwise; see the loop comment);
    KBJ = rows per substream big-window fetch. Tiles cap at
    S*R8 = 1024 fat rows; within that, the measured per-kind body/volume
    caps below (r5: presence_geom_r5.json) separate compiling shapes
    from Mosaic scoped-VMEM OOMs."""
    import math

    w = words_per_block
    if 1 + w + (1 if presence else 0) > 128:
        # the update-stream row holds block id + W mask words (+ key idx
        # for presence) in 128 lanes; w=128 (block_bits=4096) can't fit —
        # mirror the legacy kernel's w+2>128 guard so a forced
        # insert_path="sweep" gets the clean ValueError, not a negative-
        # pad trace error from _fat_stream
        return None
    J = 128 // w
    if J < 1 or w * J != 128 or nb % J:
        return None
    NBJ = nb // J
    cap = 1024
    # lambda preference: the kernel is per-window-overhead-bound, not
    # MAC-bound, so PRESENCE takes the LARGEST feasible lambda — every
    # doubling halves the per-batch window count, and the measured
    # curve is monotone across the whole feasible range: lambda 128
    # (102.1 ms) -> 256 (66.2) at B=4M (presence_geom_r5.json), 256
    # (41.6M keys/s) -> 512 (44.0M) at B=8M (geom8m_r5.json). The
    # volume/KJ caps bound lambda from above (R8=1024 at B=4M and
    # lambda=1024 at B=16M are both cap-excluded), so "largest
    # feasible" stays inside the hardware-validated envelope.
    # Insert-only/counting keep lambda ~ 128: their lambda-optimum is
    # SHAPE-DEPENDENT and 128 is the only universally-safe point
    # measured. geom_ins_r5.json (B=8M, m=2^32): lambda=256 via R8=256
    # is +3.6% insert / +2.7% counting and flat at 512 — but the same
    # lambda=256 target at m=2^34 forces R8=1024 (4x placement MACs/
    # key) and measured -12% (45.5M vs 52.0M — both rows in
    # streaming_r5.json), so a
    # global target of 256 regresses the config-3 spec point. A
    # per-(nb, B) tuned table is possible future work; presence is
    # different (largest-feasible, measured monotone at every shape
    # tried) because halved window count dominates its MAC growth.
    lam_target = 7
    candidates = []
    for r8 in (32, 64, 128, 256, 512, 1024):
        if r8 > NBJ or NBJ % r8:
            continue
        lam = batch * r8 // nb
        if lam < 8:
            continue
        score = -lam if presence else abs(math.log2(max(lam, 1)) - lam_target)
        candidates.append((score, r8, lam))
    # feasibility (grid depth, lane columns, VMEM) is checked per
    # candidate, best score first — a smaller R8 may qualify where the
    # score-best one cannot (e.g. tiny filters where P8 // S < 2)
    for _, R8, lam in sorted(candidates):
        # window slack: presence windows run 6 sigma (measured r5,
        # benchmarks/out/kj_slack_r5.json: 41.9M vs 39.8M keys/s at 8
        # sigma — every slack slot is paid in kernel slot work AND in
        # the unsort; 4 sigma overflows ~per batch and collapses to the
        # scatter fallback, 26.1M). Insert keeps 8 sigma: 6 sigma was
        # re-measured a wash (67.2M vs 67.8M, same artifact — no unsort
        # side, slimmer windows). Counting keeps 8 sigma untested.
        # Overflow is correctness-safe at any slack —
        # _fat_window_overflow routes the batch to the scatter path.
        slack = 6 if presence else 8
        kj_raw = max(
            16, (lam + max(16, int(slack * math.sqrt(lam))) + 7) // 8 * 8
        )
        if kj_raw > 1024:
            # a KJ cap at/below mean occupancy would overflow every
            # window and pay the whole sort+stream build only to fall
            # back to scatter — mirror the legacy batch//P < kmax guard
            continue
        KJ = kj_raw
        P8 = NBJ // R8
        for s in (8, 4, 2, 1):
            if P8 % s or s * R8 > cap or P8 // s < 2:
                continue
            # Mosaic's scoped-VMEM stack grows with the fully-unrolled
            # S*J*PACK inner-body count AND each body's [KJP, R8]
            # matmul operands. Bounds are measured per KERNEL KIND,
            # each just above the largest hardware-validated shape of
            # that kind and below its smallest measured OOM:
            # * presence (r5 extraction kernel,
            #   benchmarks/out/presence_geom_r5.json + the B-sweep OOM
            #   point): compiles at 128 bodies / 2.10M volume, 64
            #   bodies / 3.41M, and 128 bodies / 1.70M; OOMs at 128
            #   bodies / 3.41M (B=8M chooser corner — caught by the
            #   clean r5 B-sweep, benchmarks/out/b_sweep_r5.json), 256
            #   bodies / 4.19M, and 32 bodies / 6.03M. The bound is
            #   JOINT: volume <= 3.5M overall AND volume <= 2.2M once
            #   bodies exceed 64 (the scoped stack grows with both).
            #   (The r4 G-matmul kernel OOMed at 128 bodies outright;
            #   the extraction kernel's scoped stack is much smaller.)
            #   The bodies bound also keeps slot columns t*J+j within
            #   the 128-lane presence tile (s * J <= 128 always holds
            #   at pack=4 since s*J*pk <= 128 => s*J <= 32; at pack=1,
            #   w >= 32 so s*J <= bodies/1 <= 128 with J <= 4).
            # * counting: plane expansions OOM at 4.2M units
            #   (J=16/R8=512 requested 17.5M scoped), 2.1M validated.
            # * plain insert: bit-exact at 4.2M (probed r4); its bound
            #   only fences untested corners.
            pk = fat_pack(w, presence)
            bodies = s * J * pk
            # bodies bound per kernel kind: insert validated at 256
            # bodies (B=8M, (128, 8) — ran at 67.8M keys/s r5);
            # counting OOMs at 256 bodies even at 2.10M volume (B=8M
            # probe, r5 — its nibble plane expansions out-stack the
            # insert kernel at equal geometry) and is validated at 128;
            # presence validated at 128.
            if bodies > (256 if not (presence or counting) else 128):
                continue
            volume = bodies * _packed_rows(KJ, pk) * R8
            cap_v = (
                3_500_000 if presence
                else 2_200_000 if counting
                else 4_300_000
            )
            if presence and bodies > 64:
                cap_v = 2_200_000  # joint bound — see matrix above
            if volume > cap_v:
                continue
            kbj = ((lam * s + KJ + 64 + 7) // 8) * 8
            # scoped-VMEM estimate: double-buffered windows + block tiles
            # (the window buffers hold PACKED rows — 4 updates per
            # 128-lane row when the fields fit a 32-lane stride)
            sup_rows = _packed_rows(kbj, fat_pack(w, presence))
            if (
                2 * J * sup_rows * 128 * 4 + 4 * (s * R8 * 128 * 4)
                <= 9 * 1024 * 1024
            ):
                geom = (J, R8, s, KJ, kbj)
                if not _fat_geometry_compiles(
                    nb, w, geom, presence=presence, counting=counting,
                    batch=batch,
                ):
                    continue  # unvalidated device generation: next shape
                return geom
    return None


def _expand_bits(m: jnp.ndarray, rows: int, w: int) -> jnp.ndarray:
    """[rows, w] packed u32 words -> [rows, w*32] 0/1 planes, b-major
    (column c = b*w + word holds bit b of that word)."""
    colC = lax.broadcasted_iota(jnp.int32, (rows, w * 32), 1)
    rep = jnp.concatenate([m] * 32, axis=1)
    return (rep >> (colC // w).astype(jnp.uint32)) & _u32(1)


def _pack_planes(present_bf16: jnp.ndarray, w: int) -> jnp.ndarray:
    """[rows, w*32] 0/1 bf16 planes -> [rows, w] u32 words via exact
    matmuls (8-bit quarters then 16-bit halves; every operand/result is
    integer-exact in the matmul dtype)."""
    ccol = lax.broadcasted_iota(jnp.int32, (w * 32, 4 * w), 0)
    hcol = lax.broadcasted_iota(jnp.int32, (w * 32, 4 * w), 1)
    b_of_c = ccol // w
    w_of_c = lax.rem(ccol, w)
    pack_w = jnp.where(
        (w_of_c + (b_of_c // 8) * w) == hcol,
        (1 << lax.rem(b_of_c, 8)).astype(jnp.float32),
        jnp.float32(0),
    ).astype(jnp.bfloat16)
    quarters = lax.dot_general(
        present_bf16, pack_w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(jnp.bfloat16)
    qcol = lax.broadcasted_iota(jnp.int32, (4 * w, w), 0)
    wcol = lax.broadcasted_iota(jnp.int32, (4 * w, w), 1)
    q_of = qcol // w
    w_of = lax.rem(qcol, w)
    comb_lo = jnp.where(
        (w_of == wcol) & (q_of < 2),
        jnp.where(q_of == 0, jnp.float32(1), jnp.float32(256)),
        jnp.float32(0),
    ).astype(jnp.bfloat16)
    comb_hi = jnp.where(
        (w_of == wcol) & (q_of >= 2),
        jnp.where(q_of == 2, jnp.float32(1), jnp.float32(256)),
        jnp.float32(0),
    ).astype(jnp.bfloat16)
    lo = lax.dot_general(
        quarters, comb_lo, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    hi = lax.dot_general(
        quarters, comb_hi, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return lo.astype(jnp.int32).astype(jnp.uint32) | (
        hi.astype(jnp.int32).astype(jnp.uint32) << _u32(16)
    )


def _fat_kernel(
    starts_ref,  # SMEM [J * P8 + 1] i32 (scalar prefetch)
    upd_ref,  # ANY [BtotP, 128]: PACK updates/row at 128/PACK-lane stride
    blocks_ref,  # VMEM [S * R8, 128] fat rows (auto-streamed)
    *rest,  # out_ref [, pres_ref], sup_ref, sems
    R8: int,
    S: int,
    KJ: int,
    KBJ: int,
    P8: int,
    W: int,
    J: int,
    NBJ: int,
    PRES: bool,
    PACK: int = 1,
):
    if PRES:
        out_ref, pres_ref, sup_ref, sems = rest
    else:
        out_ref, sup_ref, sems = rest
        pres_ref = None
    p = pl.program_id(0)
    num_p = pl.num_programs(0)
    STRIDE = 128 // PACK
    KJP = _packed_rows(KJ, PACK)  # window fetch rows (packed units)
    KBJP = _packed_rows(KBJ, PACK)  # big fetch rows (packed units)

    def a_big(j, pp):
        return ((starts_ref[j * P8 + pp * S] // PACK) // _ALIGN) * _ALIGN

    def fetch(slot, pp):
        for j in range(J):
            pltpu.make_async_copy(
                upd_ref.at[pl.ds(a_big(j, pp), KBJP), :],
                sup_ref.at[slot, j],
                sems.at[slot, j],
            ).start()

    def wait(slot):
        for j in range(J):
            pltpu.make_async_copy(
                upd_ref.at[pl.ds(0, KBJP), :],
                sup_ref.at[slot, j],
                sems.at[slot, j],
            ).wait()

    slot = lax.rem(p, 2)

    @pl.when(p == 0)
    def _():
        fetch(0, 0)

    @pl.when(p + 1 < num_p)
    def _():
        fetch(1 - slot, p + 1)

    wait(slot)
    KJC = PACK * KJP  # unpacked update slots per window
    # presence slots live in a [KJC, 128] tile per grid step: slot
    # (u, packed row r) of window (j, q=p*S+t) at row u*KJP + r,
    # column t*J + j (requires S*J <= 128 — chooser-enforced). ONE
    # [KJC, 128] accumulator: per-slot values are computed at [KJP, 1]
    # (idxp1 stays a raw lane slice — those cannot sublane-concat, but
    # their COMPUTED where() outputs can), concatenated u-major to match
    # the tile row order, and merged with a single [KJC, 128] select/OR
    # per window (4 separate [KJP, 128] chains measurably pay 4x the
    # instruction issue on this overhead-bound kernel).
    pres_acc = jnp.zeros((PACK * KJP, 128), jnp.uint32) if PRES else None
    colsR = lax.broadcasted_iota(jnp.int32, (KJP, R8), 1)
    colpu = (
        lax.broadcasted_iota(jnp.int32, (KJP, 128), 1) if PRES else None
    )
    iota_r = lax.broadcasted_iota(jnp.int32, (KJP, 1), 0)
    for t in range(S):
        sl = pl.ds(t * R8, R8)
        tile = blocks_ref[sl, :]  # [R8, 128] pre-update fat rows
        base_rf = (p * S + t) * R8
        deltas = []
        for j in range(J):
            qi = j * P8 + p * S + t
            skey0 = _u32(j * NBJ) + _u32(base_rf)
            rel = ((starts_ref[qi] // PACK) // _ALIGN) * _ALIGN - a_big(j, p)
            rel = jnp.clip(rel, 0, KBJP - KJP)
            sub0 = sup_ref[slot, j, pl.ds(rel, KJP), :]  # [KJP, 128]
            a0 = a_big(j, p) + rel  # packed-row units
            end = starts_ref[qi + 1]
            # PACK update slots per fetched row, slot u at lanes
            # [u*STRIDE, u*STRIDE + 1 + W (+1)). Mosaic cannot
            # sublane-concat lane-SLICED vectors ("offset mismatch on
            # non-concat dimension"), but COMPUTED one-hots and
            # bit-planes concat fine — so each slot builds its own
            # [KJP, *] oh/bits and the window still runs ONE
            # KJC-contraction placement matmul (per-slot matmuls at
            # M=KJP measured 15% SLOWER end-to-end: the DMA they were
            # meant to amortize was already overlapped).
            # PACK=1 reduces to the original single-window pass.
            ohs, bitss = [], []
            for u in range(PACK):
                base = u * STRIDE
                rl = (sub0[:, base : base + 1] - skey0).astype(jnp.int32)
                ohs.append(
                    jnp.where(rl == colsR, jnp.float32(1), jnp.float32(0))
                )
                bitss.append(
                    _expand_bits(sub0[:, base + 1 : base + 1 + W], KJP, W)
                )
            oh_f32 = (
                jnp.concatenate(ohs, axis=0) if PACK > 1 else ohs[0]
            )  # [KJC, R8]
            bits = (
                jnp.concatenate(bitss, axis=0) if PACK > 1 else bitss[0]
            )  # [KJC, W*32]
            cnt = lax.dot_general(
                oh_f32.astype(jnp.int8), bits.astype(jnp.int8),
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )  # [R8, W*32]
            # NO in-kernel overflow chunks: a dynamic DMA loop in the body
            # defeats Mosaic's pipelining (measured +86% kernel time even
            # with zero iterations). Windows that overflow KJ (adversarial
            # duplicate skew only) are detected host-side from `starts`
            # and the WHOLE batch falls back to the sorted-scatter path
            # under lax.cond — see apply_fat_updates.
            present_pl = jnp.where(
                cnt > 0, jnp.float32(1), jnp.float32(0)
            ).astype(jnp.bfloat16)
            deltas.append(_pack_planes(present_pl, W))

            if PRES:
                # Pre-batch membership by OLD-ROW EXTRACTION, not a
                # G matmul: slot s's old block row is recovered nibble-
                # exact with the placement one-hot ([KJC, R8] @ [R8, 8W]
                # int8 — nibble values <= 15 times a 0/1 one-hot, i32
                # accumulation), then the membership test is
                # (old & mask) == mask on the nibble planes. This
                # replaced r4's G = mask_bits @ tilebits^T (a W*32-deep
                # contraction, 4x the MACs of this one) plus the
                # [R8, W*32] tile bit expansion and [KJC, W*32] npos
                # reduction that fed it — the two largest VPU surfaces
                # of the r4 presence budget (benchmarks/RESULTS_r5.md).
                # Slots whose row is outside this window extract row 0
                # garbage; `real` masks them below, as before.
                tj = tile[:, j * W : (j + 1) * W]  # [R8, W] u32
                tn = jnp.concatenate(
                    [
                        ((tj >> _u32(4 * n)) & _u32(15)).astype(jnp.int8)
                        for n in range(8)
                    ],
                    axis=1,
                )  # [R8, 8W] old-row nibbles
                rn = lax.dot_general(
                    oh_f32.astype(jnp.int8), tn, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.int32,
                )  # [KJC, 8W] per-slot old-row nibbles (one-hot-exact)
                rn_u = rn.astype(jnp.uint32)
                mns = []
                for u in range(PACK):
                    mu = sub0[:, u * STRIDE + 1 : u * STRIDE + 1 + W]
                    # computed shift/and outputs of the raw lane slice:
                    # lane-concat then sublane-concat both lower (the
                    # same pattern as the bits/one-hot builds above)
                    mns.append(
                        jnp.concatenate(
                            [(mu >> _u32(4 * n)) & _u32(15) for n in range(8)],
                            axis=1,
                        )
                    )
                mn = jnp.concatenate(mns, axis=0) if PACK > 1 else mns[0]
                okf = jnp.where(
                    (mn & rn_u) == mn, jnp.float32(1), jnp.float32(0)
                )
                hit = jnp.min(okf, axis=1, keepdims=True)  # [KJC, 1] f32
                vus = []
                for u in range(PACK):
                    # 8-aligned sublane slices of the COMPUTED hit
                    # (KJP % 8 == 0) lower fine; the raw idxp1 lane
                    # slice is used elementwise only. Each slot's value
                    # is SELECTED into its tile column BEFORE the
                    # sublane concat: a [KJP, 1] where() output keeps
                    # its source slice's lane-offset layout and Mosaic
                    # refuses to concat mismatched offsets ("offset
                    # mismatch on non-concat dimension"), while the
                    # [KJP, 128] where-broadcast is standard-layout.
                    hit_u = lax.slice_in_dim(hit, u * KJP, (u + 1) * KJP, axis=0)
                    idxp1 = sub0[
                        :, u * STRIDE + W + 1 : u * STRIDE + W + 2
                    ]  # [KJP, 1]
                    ipos = (a0 + iota_r) * PACK + u
                    real = (
                        (ipos >= starts_ref[qi]) & (ipos < end) & (idxp1 > 0)
                    )
                    hbit = jnp.where(
                        hit_u > 0.5, _u32(0x80000000), _u32(0)
                    )
                    v = jnp.where(real, idxp1 | hbit, _u32(0))
                    vus.append(jnp.where(colpu == t * J + j, v, _u32(0)))
                v128 = (
                    jnp.concatenate(vus, axis=0) if PACK > 1 else vus[0]
                )  # [KJC, 128], u-major — the tile's row order
                pres_acc = pres_acc | v128
        delta_fat = jnp.concatenate(deltas, axis=1)  # [R8, J*W = 128]
        out_ref[sl, :] = tile | delta_fat
    if PRES:
        pres_ref[:] = pres_acc


def fat_sweep_insert(
    blocks_fat: jnp.ndarray,
    upd: jnp.ndarray,
    starts: jnp.ndarray,
    *,
    J: int,
    R8: int,
    S: int,
    KJ: int,
    KBJ: int,
    W: int,
    interpret: bool = False,
    with_presence: bool = False,
    pack: int = 1,
):
    """Apply a substream-sorted update stream to the fat-row block view.

    ``blocks_fat``: ``uint32[NB/J, 128]`` (reshape of the [NB, W] array);
    ``upd``: ``uint32[Btot, 128]`` sorted by skey (col 0), masks in cols
    1..W, original index + 1 in col W+1 (presence), ``>= KBJ + 8`` rows
    of sentinel tail padding; ``starts``: ``int32[J*P8 + 1]`` window
    boundaries, j-major. Returns the updated fat view, plus — with
    presence — ``uint32[P*KJC, 128]`` slot-value tiles, where
    ``KJC = pack * _packed_rows(KJ, pack)``: slot (u, packed row r) of
    window (j, q) at row ``(q // S)*KJC + u*KJP + r``, column
    ``(q % S)*J + j``, value ``idx+1 | was_present << 31``; 0 = empty
    slot. ``_fat_unsort_presence`` is the one consumer of this layout."""
    NB8, L = blocks_fat.shape
    assert L == 128
    P8 = NB8 // R8
    P = P8 // S
    kjc = pack * _packed_rows(KJ, pack)  # presence rows per grid step
    kbjp = _packed_rows(KBJ, pack)  # big-fetch rows (packed units)
    out_shape = jax.ShapeDtypeStruct((NB8, 128), jnp.uint32)
    out_spec = pl.BlockSpec((S * R8, 128), lambda p, *_: (p, 0))
    if with_presence:
        out_shape = (
            out_shape,
            jax.ShapeDtypeStruct((P * kjc, 128), jnp.uint32),
        )
        out_spec = (out_spec, pl.BlockSpec((kjc, 128), lambda p, *_: (p, 0)))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(P,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((S * R8, 128), lambda p, *_: (p, 0)),
        ],
        out_specs=out_spec,
        scratch_shapes=[
            pltpu.VMEM((2, J, kbjp, 128), jnp.uint32),
            pltpu.SemaphoreType.DMA((2, J)),
        ],
    )
    fn = pl.pallas_call(
        functools.partial(
            _fat_kernel,
            R8=R8, S=S, KJ=KJ, KBJ=KBJ, P8=P8, W=W, J=J, NBJ=NB8,
            PRES=with_presence, PACK=pack,
        ),
        out_shape=out_shape,
        grid_spec=grid_spec,
        input_output_aliases={2: 0},
        interpret=interpret,
    )
    return fn(starts, upd, blocks_fat)


def fat_pack(w: int, presence: bool) -> int:
    """Updates per 128-lane stream row. An update needs 1 (skey) + W
    (masks/counts) + 1 (idx, presence only) lanes; when that fits a
    32-lane stride, FOUR updates share each row — 4x fewer stream bytes
    for both the host-side build write and the kernel's window fetches.
    (Sub-128-lane arrays cannot shrink the stream instead: Mosaic pads
    their HBM layout to 128 lanes and then rejects manual-DMA slices —
    measured, benchmarks/lane_probe.py.)"""
    return 4 if 1 + w + (1 if presence else 0) <= 32 else 1


def _packed_rows(n_upd: int, pack: int) -> int:
    """Fetch/window length in PACKED rows covering ``n_upd`` updates plus
    the 8-aligned fetch floor (<= 7 rows) and the end-row straddle
    (1 row), rounded to a multiple of 8. pack=1 keeps the legacy
    unpacked geometry bit-for-bit."""
    if pack == 1:
        return n_upd
    return ((n_upd // pack + _ALIGN) + 7) // 8 * 8


def _fat_stream(
    skey_sorted, masks, idx_sorted, *, J, NBJ, P8, R8, KBJ, W, pack=1
):
    """Single-pass update-stream assembly for the fat sweep: one
    concatenate builds the [Btot, 128] buffer (multiple .at[].set()
    passes measurably cost ~2 GB of extra HBM writes each at B=4M).

    With ``pack`` > 1, consecutive sorted updates share each 128-lane
    row at a ``128 // pack``-lane stride (update u of packed row r is
    update ``r * pack + u`` of the sorted stream — a plain row-major
    fold, so one XLA reshape builds it). ``starts`` stays in UPDATE
    units; the kernel converts to packed rows."""
    B = masks.shape[0]
    cols = [skey_sorted.astype(jnp.uint32)[:, None], masks]
    ncols = 1 + W
    if idx_sorted is not None:
        cols.append(idx_sorted.astype(jnp.uint32)[:, None])
        ncols += 1
    core = jnp.concatenate(cols, axis=1)
    jq = jnp.arange(J * P8 + 1, dtype=jnp.int32)
    tgt = jnp.where(
        jq == J * P8, J * NBJ, (jq // P8) * NBJ + (jq % P8) * R8
    ).astype(jnp.int32)
    starts = jnp.searchsorted(skey_sorted.astype(jnp.int32), tgt).astype(
        jnp.int32
    )
    if pack == 1:
        pad = KBJ + _ALIGN
        # jnp.pad lowers to one fused write here; concatenating explicit
        # zero blocks measurably costs ~2x (2 GB array at B=4M)
        upd = jnp.pad(core, ((0, pad), (0, 128 - ncols)))
        upd = upd.at[B:, 0].set(jnp.uint32(J * NBJ))
        return upd, starts
    stride = 128 // pack
    kbjp = _packed_rows(KBJ, pack)
    btot_p = -(-B // pack) + kbjp + _ALIGN
    padrows = btot_p * pack - B
    wide = jnp.pad(core, ((0, padrows), (0, stride - ncols)))
    wide = wide.at[B:, 0].set(jnp.uint32(J * NBJ))
    return wide.reshape(btot_p, 128), starts


def _fat_window_overflow(starts, *, J, P8, S, KJ, KBJ, pack=1):
    """True if any (j, q) window cannot cover its slice from the clamped
    KJ-row fetch. The fat kernel has NO chunk loop (rows beyond the KJ
    window are silently never applied), so on overflow apply_fat_updates
    routes the WHOLE batch — insert AND presence — to the sorted-scatter
    branch under lax.cond; that branch is the only thing keeping
    overflowing batches correct. The packed arithmetic mirrors the
    kernel's exactly (same floor/clip in packed-row units)."""
    s = starts
    jq = jnp.arange(J * P8, dtype=jnp.int32)
    big_idx = (jq // P8) * P8 + ((jq % P8) // S) * S
    if pack == 1:
        a_big = (s[big_idx] // _ALIGN) * _ALIGN
        a = a_big + jnp.clip((s[jq] // _ALIGN) * _ALIGN - a_big, 0, KBJ - KJ)
        return jnp.max(s[jq + 1] - a) > KJ
    kjp = _packed_rows(KJ, pack)
    kbjp = _packed_rows(KBJ, pack)
    a_big = ((s[big_idx] // pack) // _ALIGN) * _ALIGN
    r4 = ((s[jq] // pack) // _ALIGN) * _ALIGN
    a = a_big + jnp.clip(r4 - a_big, 0, kbjp - kjp)
    need_end = -(-(s[jq + 1]) // pack)  # ceil in packed rows
    return jnp.max(need_end - a) > kjp


def _fat_unsort_presence(presb, starts, B, *, J, NBJ, P8, R8, S, KJ, KBJ):
    """Presence tiles -> bool[B] in original key order via the vkey
    single-column unsort (idx+1 rides bits 1.., verdict the LSB; empty
    slots sink to the tail). ``KJ`` here is the slots per window (KJC =
    pack * KJP when the stream is packed); window (j, q) rides column
    t*J + j of its grid step's tile."""
    P = P8 // S
    jq = jnp.arange(J * P8, dtype=jnp.int32)
    j = jq // P8
    q = jq % P8
    p0 = q // S
    t = q % S
    presT = presb.reshape(P, KJ, 128).transpose(0, 2, 1).reshape(P * 128, KJ)
    v = presT[p0 * 128 + t * J + j]  # [J*P8, KJ]
    vkey = jnp.where(
        v == 0,
        _u32(0xFFFFFFFE),  # even: empty slots must read as hit=0
        ((v & _u32(0x7FFFFFFF)) << _u32(1)) | (v >> _u32(31)),
    ).reshape(-1)
    (skey,) = lax.sort((vkey,), num_keys=1)
    return (skey[:B] & _u32(1)) == 1


def apply_fat_updates(
    blocks: jnp.ndarray,
    blk: jnp.ndarray,
    bit: jnp.ndarray,
    valid: jnp.ndarray,
    *,
    block_bits: int,
    params,
    interpret: bool | None = None,
    idx: jnp.ndarray | None = None,
    storage_fat: bool = False,
):
    """Fat-sweep counterpart of :func:`apply_blocked_updates`; ``params``
    from :func:`choose_fat_params`.

    Windows that overflow their KJ fetch (adversarial duplicate skew —
    uniform keys sit 8 sigma below) route the WHOLE batch to the
    sorted-scatter path under ``lax.cond``: the kernel itself carries no
    chunk loop (a dynamic DMA loop in the body measurably defeats
    Mosaic's pipelining even at zero iterations).

    Returns the new blocks ([NB, W]); with ``idx`` (original key
    indices, 1-based — presence mode) returns ``(new_blocks,
    present[B])`` where ``present`` is each key's PRE-batch membership.

    Presence CONTRACT (same as the legacy kernel): invalid entries
    (``valid`` False) must form a TAIL SUFFIX of the batch
    (tpubloom.filter._pack_padded guarantees this). Invalid keys emit no
    presence slot, so a mid-batch invalid entry would shift every later
    key's verdict by one in the index-sorted unsort; tail padding keeps
    valid indices contiguous (1..V) and padded entries correctly read
    False from the empty-slot fillers.

    ``storage_fat``: ``blocks`` is already the fat [NB/J, 128] view and
    the fat view is returned — no reshape at the kernel boundary (XLA's
    tiled HBM layouts make [NB, W] <-> fat reshapes REAL copies, ~26 ms
    per pass at m=2^32; persistent filters keep their storage fat).
    """
    w = block_bits // 32
    J0, R8, S, KJ, KBJ = params
    nb = blocks.size // w
    B = blk.shape[0]
    J = J0
    NBJ = nb // J
    P8 = NBJ // R8
    interp = jax.default_backend() == "cpu" if interpret is None else interpret
    blkv = jnp.where(valid, blk, nb)
    j_of = (blkv % J).astype(jnp.uint32)
    rf_of = (blkv // J).astype(jnp.uint32)
    skey = jnp.where(valid, j_of * NBJ + rf_of, _u32(J * NBJ))
    cols, nbits, packed = _pack_positions(bit, block_bits, bit.shape[-1])
    extra = (idx,) if idx is not None else ()
    sorted_cols = lax.sort((skey,) + cols + extra, num_keys=1)
    ss = sorted_cols[0]
    pcols = sorted_cols[1:-1] if idx is not None else sorted_cols[1:]
    bit_sorted = _unpack_positions(
        pcols, block_bits, bit.shape[-1], nbits, packed
    )
    masks = blocked.build_masks(bit_sorted, w)
    idx_sorted = sorted_cols[-1] if idx is not None else None
    pack = fat_pack(w, idx is not None)
    upd, starts = _fat_stream(
        ss, masks, idx_sorted, J=J, NBJ=NBJ, P8=P8, R8=R8, KBJ=KBJ, W=w,
        pack=pack,
    )
    overflow = _fat_window_overflow(
        starts, J=J, P8=P8, S=S, KJ=KJ, KBJ=KBJ, pack=pack
    )

    def to_fat(bl):
        return bl if storage_fat else bl.reshape(NBJ, 128)

    def from_fat(bl_fat):
        return bl_fat if storage_fat else bl_fat.reshape(nb, w)

    def _scatter_coords():
        """(row, masks) for the fallback in whichever view ``blocks``
        is stored — the fat fold keeps the fallback reshape-free
        (a fat <-> [NB, W] reshape is a real copy on TPU)."""
        masks_orig = blocked.build_masks(bit, w)
        if storage_fat:
            return blocked.fat_fold_masks(blk, masks_orig, J)
        return blk, masks_orig

    if idx is None:

        def fat_branch(ops):
            bl, u, st = ops
            return from_fat(
                fat_sweep_insert(
                    to_fat(bl), u, st,
                    J=J, R8=R8, S=S, KJ=KJ, KBJ=KBJ, W=w, interpret=interp,
                    pack=pack,
                )
            )

        def scatter_branch(ops):
            bl, u, st = ops
            row, masks_orig = _scatter_coords()
            return blocked.blocked_insert(bl, row, masks_orig, valid)

        return lax.cond(overflow, scatter_branch, fat_branch, (blocks, upd, starts))

    def fat_branch(ops):
        bl, u, st = ops
        new_fat, presb = fat_sweep_insert(
            to_fat(bl), u, st,
            J=J, R8=R8, S=S, KJ=KJ, KBJ=KBJ, W=w,
            interpret=interp, with_presence=True, pack=pack,
        )
        present = _fat_unsort_presence(
            presb, st, B, J=J, NBJ=NBJ, P8=P8, R8=R8, S=S,
            KJ=pack * _packed_rows(KJ, pack), KBJ=KBJ,
        )
        return from_fat(new_fat), present

    def scatter_branch(ops):
        bl, u, st = ops
        row, masks_orig = _scatter_coords()
        nrows = bl.shape[0]
        rows = bl[jnp.minimum(jnp.where(valid, row, 0), nrows - 1)]
        hit = jnp.all((rows & masks_orig) == masks_orig, axis=-1)
        present = hit & valid
        out = blocked.blocked_insert(bl, row, masks_orig, valid)
        return out, present

    return lax.cond(overflow, scatter_branch, fat_branch, (blocks, upd, starts))


def _fat_count_kernel(
    starts_ref,  # SMEM [J * P8 + 1] i32 (scalar prefetch)
    upd_ref,  # ANY [Btot, 128]: col 0 skey, 1..W packed nibble counts
    blocks_ref,  # VMEM [S * R8, 128] fat counter rows (auto-streamed)
    out_ref,  # VMEM [S * R8, 128]
    sup_ref,  # VMEM scratch [2, J, KBJ, 128] u32
    sems,  # DMA sems [2, J]
    *,
    R8: int,
    S: int,
    KJ: int,
    KBJ: int,
    P8: int,
    W: int,
    J: int,
    NBJ: int,
    INCREMENT: bool,
    PACK: int = 1,
):
    """Fat-row blocked-counting sweep: saturating nibble add/subtract on
    the [NB/J, 128] counter view (same substream-sorted stream layout as
    :func:`_fat_kernel`, including the PACK-updates-per-row stream; same
    one-clamp-per-batch semantics as :func:`_count_kernel` — counts are
    additive so there is no merge or presence machinery, and like the fat
    bit kernel there is NO in-kernel chunk loop: window overflow routes
    the batch to the scatter fallback host-side)."""
    p = pl.program_id(0)
    num_p = pl.num_programs(0)
    STRIDE = 128 // PACK
    KJP = _packed_rows(KJ, PACK)
    KBJP = _packed_rows(KBJ, PACK)

    def a_big(j, pp):
        return ((starts_ref[j * P8 + pp * S] // PACK) // _ALIGN) * _ALIGN

    def fetch(slot, pp):
        for j in range(J):
            pltpu.make_async_copy(
                upd_ref.at[pl.ds(a_big(j, pp), KBJP), :],
                sup_ref.at[slot, j],
                sems.at[slot, j],
            ).start()

    def wait(slot):
        for j in range(J):
            pltpu.make_async_copy(
                upd_ref.at[pl.ds(0, KBJP), :],
                sup_ref.at[slot, j],
                sems.at[slot, j],
            ).wait()

    slot = lax.rem(p, 2)

    @pl.when(p == 0)
    def _():
        fetch(0, 0)

    @pl.when(p + 1 < num_p)
    def _():
        fetch(1 - slot, p + 1)

    wait(slot)
    CPB = W * 8  # nibble planes per block
    colC = lax.broadcasted_iota(jnp.int32, (KJP, CPB), 1)
    colsR = lax.broadcasted_iota(jnp.int32, (KJP, R8), 1)
    tcolC = lax.broadcasted_iota(jnp.int32, (R8, CPB), 1)
    # block-diagonal plane->word pack weights, one [J*CPB, 128] matrix
    # per byte q: plane (j, n*W + w) contributes 1 (n even) or 16 (n
    # odd) to lane j*W + w when n // 2 == q (same exact-byte matmul
    # trick as _count_kernel, widened to the full fat row so each
    # sub-tile packs with 4 matmuls instead of 4*J narrow ones)
    pcJ = lax.broadcasted_iota(jnp.int32, (J * CPB, 128), 0)
    lnJ = lax.broadcasted_iota(jnp.int32, (J * CPB, 128), 1)
    j_of = pcJ // CPB
    n_of = lax.rem(pcJ, CPB) // W
    w_of = lax.rem(pcJ, W)
    lane_match = lnJ == j_of * W + w_of
    pack_qs = []
    for q in range(4):
        pack_qs.append(
            jnp.where(
                lane_match & (n_of // 2 == q),
                jnp.where(lax.rem(n_of, 2) == 0, jnp.float32(1), jnp.float32(16)),
                jnp.float32(0),
            ).astype(jnp.bfloat16)
        )
    for t in range(S):
        sl = pl.ds(t * R8, R8)
        tile = blocks_ref[sl, :]  # [R8, 128] pre-update fat counter rows
        base_rf = (p * S + t) * R8
        news = []
        for j in range(J):
            qi = j * P8 + p * S + t
            skey0 = _u32(j * NBJ) + _u32(base_rf)
            rel = ((starts_ref[qi] // PACK) // _ALIGN) * _ALIGN - a_big(j, p)
            rel = jnp.clip(rel, 0, KBJP - KJP)
            sub = sup_ref[slot, j, pl.ds(rel, KJP), :]  # [KJP, 128]
            # per-slot COMPUTED one-hots/nibble-planes concat along the
            # contraction axis (raw lane slices cannot sublane-concat in
            # Mosaic, computed values can), so the window still runs ONE
            # KJC-contraction matmul. PACK=1 reduces to the original
            # single pass.
            ohs, nibfs = [], []
            for u in range(PACK):
                base = u * STRIDE
                rl = (sub[:, base : base + 1] - skey0).astype(jnp.int32)
                ohs.append(
                    jnp.where(
                        rl == colsR, jnp.float32(1), jnp.float32(0)
                    ).astype(jnp.bfloat16)
                )  # [KJP, R8]; sentinels match nothing
                m = sub[:, base + 1 : base + 1 + W]  # [KJP, W] nibbles
                rep = jnp.concatenate([m] * 8, axis=1)  # [KJP, CPB]
                nib = (
                    rep >> ((colC // W).astype(jnp.uint32) * _u32(4))
                ) & _u32(15)
                nibfs.append(
                    nib.astype(jnp.int32)
                    .astype(jnp.float32)
                    .astype(jnp.bfloat16)
                )
            oh = jnp.concatenate(ohs, axis=0) if PACK > 1 else ohs[0]
            nibf = jnp.concatenate(nibfs, axis=0) if PACK > 1 else nibfs[0]
            cnts = lax.dot_general(
                oh, nibf, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [R8, CPB], exact (<= 15 * KJP * PACK < 2^24)
            acc = jnp.minimum(cnts, jnp.float32(16))
            tj = tile[:, j * W : (j + 1) * W]
            trep = jnp.concatenate([tj] * 8, axis=1)  # [R8, CPB]
            old = (trep >> ((tcolC // W).astype(jnp.uint32) * _u32(4))) & _u32(15)
            oldf = old.astype(jnp.int32).astype(jnp.float32)
            if INCREMENT:
                new = jnp.minimum(oldf + acc, jnp.float32(15))
            else:
                new = jnp.maximum(oldf - acc, jnp.float32(0))
            news.append(new.astype(jnp.bfloat16))  # <= 15, bf16-exact
        new_all = jnp.concatenate(news, axis=1)  # [R8, J*CPB]
        packed = jnp.zeros((R8, 128), jnp.uint32)
        for q in range(4):
            byte = lax.dot_general(
                new_all, pack_qs[q], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [R8, 128] f32-exact bytes
            packed = packed | (
                byte.astype(jnp.int32).astype(jnp.uint32) << _u32(8 * q)
            )
        out_ref[sl, :] = packed


def fat_sweep_counter(
    blocks_fat: jnp.ndarray,
    upd: jnp.ndarray,
    starts: jnp.ndarray,
    *,
    J: int,
    R8: int,
    S: int,
    KJ: int,
    KBJ: int,
    W: int,
    increment: bool,
    interpret: bool = False,
    pack: int = 1,
) -> jnp.ndarray:
    """Apply a substream-sorted nibble-count stream to the fat counter
    view. Same stream contract as :func:`fat_sweep_insert` with cols
    1..W carrying packed 4-bit per-counter multiplicities instead of OR
    masks."""
    NB8, L = blocks_fat.shape
    assert L == 128
    P8 = NB8 // R8
    P = P8 // S
    kbjp = _packed_rows(KBJ, pack)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(P,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((S * R8, 128), lambda p, *_: (p, 0)),
        ],
        out_specs=pl.BlockSpec((S * R8, 128), lambda p, *_: (p, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, J, kbjp, 128), jnp.uint32),
            pltpu.SemaphoreType.DMA((2, J)),
        ],
    )
    fn = pl.pallas_call(
        functools.partial(
            _fat_count_kernel,
            R8=R8, S=S, KJ=KJ, KBJ=KBJ, P8=P8, W=W, J=J, NBJ=NB8,
            INCREMENT=increment, PACK=pack,
        ),
        out_shape=jax.ShapeDtypeStruct((NB8, 128), jnp.uint32),
        grid_spec=grid_spec,
        input_output_aliases={2: 0},
        interpret=interpret,
    )
    return fn(starts, upd, blocks_fat)


def apply_fat_counter_updates(
    blocks: jnp.ndarray,
    blk: jnp.ndarray,
    cpos: jnp.ndarray,
    valid: jnp.ndarray,
    *,
    counters_per_block: int,
    k: int,
    increment: bool,
    params,
    interpret: bool | None = None,
    storage_fat: bool = False,
) -> jnp.ndarray:
    """Fat-sweep counterpart of :func:`apply_counter_updates`; ``params``
    from :func:`choose_fat_params` (presence=False — counting has no
    fused-presence variant). Window overflow (adversarial duplicate
    skew) routes the WHOLE batch to the flat scatter fallback under
    ``lax.cond``, exactly like :func:`apply_fat_updates`.

    ``storage_fat``: ``blocks`` is already the fat [NB/J, 128] view and
    the fat view is returned."""
    from tpubloom.ops import counting

    J0, R8, S, KJ, KBJ = params
    cpb = counters_per_block
    w = cpb // 8
    nb = blocks.size // w
    B = blk.shape[0]
    J = J0
    NBJ = nb // J
    P8 = NBJ // R8
    interp = jax.default_backend() == "cpu" if interpret is None else interpret
    blkv = jnp.where(valid, blk, nb)
    j_of = (blkv % J).astype(jnp.uint32)
    rf_of = (blkv // J).astype(jnp.uint32)
    skey = jnp.where(valid, j_of * NBJ + rf_of, _u32(J * NBJ))
    cols, nbits, packed = _pack_positions(cpos, cpb, k)
    sorted_cols = lax.sort((skey,) + cols, num_keys=1)
    ss = sorted_cols[0]
    cpos_s = _unpack_positions(sorted_cols[1:], cpb, k, nbits, packed)
    # per-key multiplicity of each counter, 4-bit nibbles in the counter
    # storage (word, nibble) layout (multiplicity <= k <= 15)
    planes = jnp.zeros((B, cpb), jnp.uint32)
    iota_c = lax.broadcasted_iota(jnp.uint32, (B, cpb), 1)
    for i in range(k):
        planes = planes + (cpos_s[:, i : i + 1] == iota_c).astype(jnp.uint32)
    pw = planes.reshape(B, w, 8)
    shifts = (jnp.arange(8, dtype=jnp.uint32) * 4)[None, None, :]
    cnt_words = jnp.sum(pw << shifts, axis=2, dtype=jnp.uint32)  # [B, W]
    pack = fat_pack(w, False)
    upd, starts = _fat_stream(
        ss, cnt_words, None, J=J, NBJ=NBJ, P8=P8, R8=R8, KBJ=KBJ, W=w,
        pack=pack,
    )
    overflow = _fat_window_overflow(
        starts, J=J, P8=P8, S=S, KJ=KJ, KBJ=KBJ, pack=pack
    )

    def fat_branch(ops):
        bl, u, st = ops
        out = fat_sweep_counter(
            bl if storage_fat else bl.reshape(NBJ, 128), u, st,
            J=J, R8=R8, S=S, KJ=KJ, KBJ=KBJ, W=w,
            increment=increment, interpret=interp, pack=pack,
        )
        return out if storage_fat else out.reshape(nb, w)

    def scatter_branch(ops):
        bl, u, st = ops
        gpos = (blk[:, None] * cpb + cpos.astype(jnp.int32)).astype(jnp.int32)
        valid_k = jnp.broadcast_to(valid[:, None], gpos.shape)
        out = counting.counter_update(
            bl.reshape(-1), gpos.ravel(), valid_k.ravel(), increment=increment
        )
        return out.reshape(blocks.shape)

    return lax.cond(overflow, scatter_branch, fat_branch, (blocks, upd, starts))


def make_sweep_insert_fn(
    config, *, interpret: bool | None = None, with_presence: bool = False,
    storage_fat: bool = False,
):
    """Pure ``(blocks, keys_u8, lengths) -> blocks`` blocked insert via the
    partition sweep. Bit-identical to
    :func:`tpubloom.filter.make_blocked_insert_fn` (same blocked spec).

    With ``with_presence`` the function returns ``(blocks, present)``
    where ``present[i]`` says whether key i was in the filter BEFORE this
    batch (test-and-insert — the semantics of the reference's Lua add
    script, which returns prior membership). Within-batch duplicates all
    report the pre-batch state. Requires batch padding (lengths < 0) to
    sit at the TAIL of the batch (tpubloom.filter._pack_padded
    guarantees this); padded entries return False.

    ``storage_fat``: blocks are the fat [NB/J, 128] view in AND out (the
    persistent-filter layout; avoids reshape copies at the kernel
    boundary). Batches the fat kernel cannot take reshape to the
    logical view internally.
    """
    nb, bb, w = config.n_blocks, config.block_bits, config.words_per_block
    k, seed, bh = config.k, config.seed, config.block_hash

    def insert(blocks, keys_u8, lengths):
        B = keys_u8.shape[0]
        fat_shape = blocks.shape if storage_fat else None
        # legacy-kernel shape guards apply only when the fat sweep does
        # not take the batch (apply_blocked_updates / the presence branch
        # below prefer it)
        has_fat = choose_fat_params(nb, B, w, presence=with_presence) is not None
        R, KMAX = choose_params(nb, B)
        if not has_fat and (nb % R != 0 or w + 2 > 128 or R % 32 != 0):
            # partitions must tile the array exactly (or trailing blocks
            # would silently never receive updates), the 128-lane update
            # row must fit block id + W mask words + key idx, and R must
            # be a multiple of 32 for the Kronecker one-hot split
            raise ValueError(
                f"sweep insert does not support this shape (n_blocks={nb}, "
                f"R={R}, words_per_block={w}) — use insert_path='scatter'"
            )
        if with_presence and not has_fat and (nb // R) * KMAX < B:
            # the presence output has one slot per chunk-0 window entry;
            # batches larger than P*KMAX cannot all be answered (auto
            # never picks such shapes — only a forced 'sweep' gets here)
            raise ValueError(
                f"sweep test-and-insert needs P*KMAX >= batch "
                f"({(nb // R) * KMAX} < {B}) — use insert_path='scatter'"
            )
        P = nb // R
        interp = (
            jax.default_backend() == "cpu" if interpret is None else interpret
        )
        valid = lengths >= 0
        blk, bit = blocked.block_positions(
            keys_u8, jnp.maximum(lengths, 0),
            n_blocks=nb, block_bits=bb, k=k, seed=seed, block_hash=bh,
        )
        if not with_presence:
            fat = choose_fat_params(nb, B, w)
            if fat is not None:
                return apply_fat_updates(
                    blocks, blk, bit, valid,
                    block_bits=bb, params=fat, interpret=interpret,
                    storage_fat=storage_fat,
                )
            out = apply_blocked_updates(
                blocks.reshape(nb, w) if storage_fat else blocks,
                blk, bit, valid, block_bits=bb, interpret=interpret,
            )
            return out.reshape(fat_shape) if storage_fat else out
        fat = choose_fat_params(nb, B, w, presence=True)
        if fat is not None:
            idx0 = jnp.arange(1, B + 1, dtype=jnp.uint32)  # 0 = empty slot
            return apply_fat_updates(
                blocks, blk, bit, valid,
                block_bits=bb, params=fat, interpret=interpret, idx=idx0,
                storage_fat=storage_fat,
            )
        if storage_fat:
            blocks = blocks.reshape(nb, w)
        blk = jnp.where(valid, blk, nb)
        cols, nbits, packed = _pack_positions(bit, bb, k)
        idx0 = jnp.arange(1, B + 1, dtype=jnp.uint32)  # 0 = filler
        cols = cols + (idx0,)
        sorted_cols = lax.sort((blk,) + cols, num_keys=1)
        bs = sorted_cols[0]
        bit_sorted = _unpack_positions(sorted_cols[1:-1], bb, k, nbits, packed)
        masks = blocked.build_masks(bit_sorted, w)
        # sentinel rows carry zero masks (their positions are real hash
        # bits of padding keys; they never reach a partition, but keep
        # the invariant obvious)
        starts, upd = _stream_scaffold(bs, nb, P, R, KMAX)
        upd = upd.at[:B, 1 : w + 1].set(masks)

        upd = upd.at[:B, w + 1].set(sorted_cols[-1])
        # chunk-0 windows cover [align8(starts[p]), +KMAX); a partition
        # whose slice exceeds that emits no presence for the overflow —
        # rare (KMAX covers lambda+8sigma; needs adversarial duplicate
        # skew), handled by a gather-query fallback on the PRE-batch
        # array, computed under lax.cond so the common path never pays.
        span = starts[1:] - (starts[:-1] // _ALIGN) * _ALIGN
        overflow = jnp.max(span) > KMAX

        def gather_presence():
            rows = blocks[jnp.minimum(blk, nb - 1)]
            masks_orig = blocked.build_masks(bit, w)
            hit = jnp.all((rows & masks_orig) == masks_orig, axis=-1)
            return hit & valid & (blk < nb)

        presence_fb = lax.cond(
            overflow,
            gather_presence,
            lambda: jnp.zeros((B,), bool),
        )
        new_blocks, pres_packed = sweep_insert(
            blocks, upd, starts,
            R=R, KMAX=KMAX, interpret=interp, with_presence=True,
        )
        v = pres_packed.reshape(P, 8, KMAX // 8).transpose(0, 2, 1).reshape(-1)
        # single-column unsort: key = (idx+1) << 1 | hit sorts by original
        # index with the verdict riding the LSB; filler slots (v == 0) map
        # to the max key and sink to the tail
        vkey = jnp.where(
            v == 0,
            _u32(0xFFFFFFFE),  # even: filler slots must read as hit=0
            ((v & _u32(0x7FFFFFFF)) << _u32(1)) | (v >> _u32(31)),
        )
        (skey,) = lax.sort((vkey,), num_keys=1)
        fused = (skey[:B] & _u32(1)) == 1
        present = jnp.where(overflow, presence_fb, fused)
        if storage_fat:
            new_blocks = new_blocks.reshape(fat_shape)
        return new_blocks, present

    return insert


# =========================================================================
# Read-only fat query sweep — the dedicated query kernel (ISSUE 12)
# =========================================================================
#
# Why a query kernel at all: RESULTS_r5 §4 fenced every GATHER-based
# query at ~60M keys/s (XLA's row gather serves one row per ~12.3 ns
# regardless of locality) and measured the full gather query at 41.7M
# (BENCH r05 query_only) — the read path is now the slow half of the
# device-speed gap (insert-only runs 67.7M). §4 also argued a sweep
# query "would be a wash" against the FUSED kernel's front-end — but
# that arithmetic charged the query the fused kernel's whole budget.
# RESULTS_r5 §2 proved the sweep family is per-window-OVERHEAD-bound,
# not MXU-bound, and the fused kernel's window cost is dominated by the
# machinery a pure query never needs:
#
# * no delta: the placement cnt matmul ([KJC, R8]^T @ [KJC, W*32] int8,
#   the kernel's largest contraction), the bit-plane expansion of the
#   update stream, and the plane->word pack matmuls all vanish;
# * no write-back: blocks stream HBM->VMEM only (half the array DMA),
#   there is no donated-blocks chain, and the output is just the
#   presence tiles — so query steps need no buffer donation and can
#   pipeline against a concurrent reader;
# * no counter planes, no merge/representative selection.
#
# What remains per window is exactly the r5 extraction trick
# (RESULTS_r5 §1): one placement one-hot, ONE [KJC, R8] @ [R8, 8W] int8
# nibble-extraction matmul, the (mask & row) == mask VPU test, and the
# slot-value pack — the lightest member of the sweep family. The
# front-end (skey sort + stream build) and the unsort are shared with
# the fused kernel and already floor-proofed stage by stage (§6b).
#
# Geometry: the scoped-VMEM update/delta buffers are gone, so query
# tiles can run LARGER lambda than presence tiles at equal footprint
# (choose_fat_query_params relaxes the scoped estimate accordingly).
# There is no hardware-validated signature set yet — every geometry
# probe-compiles through the PR-11 machinery (AOT, per-process cache +
# per-device-kind persistent disk cache), so an unvalidated shape
# demotes to the gather path instead of erroring at first use.
# benchmarks/profile_query.py is the per-stage harness;
# benchmarks/query_load.py asserts path selection + bit-exactness and
# gates the served (coalesced) read path.


def _fat_query_kernel(
    starts_ref,  # SMEM [J * P8 + 1] i32 (scalar prefetch)
    upd_ref,  # ANY [BtotP, 128]: PACK queries/row — skey, masks, idx+1
    blocks_ref,  # VMEM [S * R8, 128] fat rows (auto-streamed, read-only)
    pres_ref,  # VMEM [KJC, 128] presence tile for this grid step
    sup_ref,  # VMEM scratch [2, J, KBJP, 128] u32
    sems,  # DMA sems [2, J]
    *,
    R8: int,
    S: int,
    KJ: int,
    KBJ: int,
    P8: int,
    W: int,
    J: int,
    NBJ: int,
    PACK: int = 1,
):
    """Membership-only fat sweep: the :func:`_fat_kernel` presence half
    with the whole update/delta machinery deleted. Same substream-sorted
    stream layout (col 0 skey, 1..W mask words, W+1 idx+1), same
    double-buffered window fetches, same slot-tile output consumed by
    :func:`_fat_unsort_presence` — but ``blocks_ref`` is never written
    (no ``input_output_aliases``, no donation) and the only output is
    the presence tiles. Like the fat insert kernel there is NO in-kernel
    chunk loop: window overflow (adversarial duplicate skew) is detected
    host-side and the whole batch takes the gather fallback."""
    p = pl.program_id(0)
    num_p = pl.num_programs(0)
    STRIDE = 128 // PACK
    KJP = _packed_rows(KJ, PACK)
    KBJP = _packed_rows(KBJ, PACK)

    def a_big(j, pp):
        return ((starts_ref[j * P8 + pp * S] // PACK) // _ALIGN) * _ALIGN

    def fetch(slot, pp):
        for j in range(J):
            pltpu.make_async_copy(
                upd_ref.at[pl.ds(a_big(j, pp), KBJP), :],
                sup_ref.at[slot, j],
                sems.at[slot, j],
            ).start()

    def wait(slot):
        for j in range(J):
            pltpu.make_async_copy(
                upd_ref.at[pl.ds(0, KBJP), :],
                sup_ref.at[slot, j],
                sems.at[slot, j],
            ).wait()

    slot = lax.rem(p, 2)

    @pl.when(p == 0)
    def _():
        fetch(0, 0)

    @pl.when(p + 1 < num_p)
    def _():
        fetch(1 - slot, p + 1)

    wait(slot)
    # presence slots in a [KJC, 128] tile per grid step, slot (u, packed
    # row r) of window (j, t) at row u*KJP + r, column t*J + j — the
    # exact layout _fat_kernel emits, so the unsort is shared verbatim
    pres_acc = jnp.zeros((PACK * KJP, 128), jnp.uint32)
    colsR = lax.broadcasted_iota(jnp.int32, (KJP, R8), 1)
    colpu = lax.broadcasted_iota(jnp.int32, (KJP, 128), 1)
    iota_r = lax.broadcasted_iota(jnp.int32, (KJP, 1), 0)
    for t in range(S):
        sl = pl.ds(t * R8, R8)
        tile = blocks_ref[sl, :]  # [R8, 128] fat rows (never written)
        base_rf = (p * S + t) * R8
        for j in range(J):
            qi = j * P8 + p * S + t
            skey0 = _u32(j * NBJ) + _u32(base_rf)
            rel = ((starts_ref[qi] // PACK) // _ALIGN) * _ALIGN - a_big(j, p)
            rel = jnp.clip(rel, 0, KBJP - KJP)
            sub0 = sup_ref[slot, j, pl.ds(rel, KJP), :]  # [KJP, 128]
            a0 = a_big(j, p) + rel  # packed-row units
            end = starts_ref[qi + 1]
            # per-slot COMPUTED one-hots concat along the contraction
            # axis (raw lane slices cannot sublane-concat in Mosaic,
            # computed values can — the _fat_kernel pattern)
            ohs = []
            for u in range(PACK):
                base = u * STRIDE
                rl = (sub0[:, base : base + 1] - skey0).astype(jnp.int32)
                ohs.append(
                    jnp.where(rl == colsR, jnp.float32(1), jnp.float32(0))
                )
            oh_f32 = (
                jnp.concatenate(ohs, axis=0) if PACK > 1 else ohs[0]
            )  # [KJC, R8]
            # membership by OLD-ROW NIBBLE EXTRACTION (RESULTS_r5 §1):
            # recover each slot's block row nibble-exact through the
            # placement one-hot (int8 matmul, one-hot x values <= 15,
            # i32 accumulation), then test (mask & row) == mask on the
            # nibble planes. Slots whose row is outside this window
            # extract row 0 garbage; `real` masks them below.
            tj = tile[:, j * W : (j + 1) * W]  # [R8, W] u32
            tn = jnp.concatenate(
                [
                    ((tj >> _u32(4 * n)) & _u32(15)).astype(jnp.int8)
                    for n in range(8)
                ],
                axis=1,
            )  # [R8, 8W] row nibbles
            rn = lax.dot_general(
                oh_f32.astype(jnp.int8), tn, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )  # [KJC, 8W] per-slot row nibbles (one-hot-exact)
            rn_u = rn.astype(jnp.uint32)
            mns = []
            for u in range(PACK):
                mu = sub0[:, u * STRIDE + 1 : u * STRIDE + 1 + W]
                mns.append(
                    jnp.concatenate(
                        [(mu >> _u32(4 * n)) & _u32(15) for n in range(8)],
                        axis=1,
                    )
                )
            mn = jnp.concatenate(mns, axis=0) if PACK > 1 else mns[0]
            okf = jnp.where(
                (mn & rn_u) == mn, jnp.float32(1), jnp.float32(0)
            )
            hit = jnp.min(okf, axis=1, keepdims=True)  # [KJC, 1] f32
            vus = []
            for u in range(PACK):
                hit_u = lax.slice_in_dim(hit, u * KJP, (u + 1) * KJP, axis=0)
                idxp1 = sub0[
                    :, u * STRIDE + W + 1 : u * STRIDE + W + 2
                ]  # [KJP, 1]
                ipos = (a0 + iota_r) * PACK + u
                real = (ipos >= starts_ref[qi]) & (ipos < end) & (idxp1 > 0)
                hbit = jnp.where(hit_u > 0.5, _u32(0x80000000), _u32(0))
                v = jnp.where(real, idxp1 | hbit, _u32(0))
                vus.append(jnp.where(colpu == t * J + j, v, _u32(0)))
            v128 = (
                jnp.concatenate(vus, axis=0) if PACK > 1 else vus[0]
            )  # [KJC, 128], u-major
            pres_acc = pres_acc | v128
    pres_ref[:] = pres_acc


def fat_sweep_query(
    blocks_fat: jnp.ndarray,
    upd: jnp.ndarray,
    starts: jnp.ndarray,
    *,
    J: int,
    R8: int,
    S: int,
    KJ: int,
    KBJ: int,
    W: int,
    interpret: bool = False,
    pack: int = 1,
) -> jnp.ndarray:
    """Run the read-only query sweep over the fat block view.

    Same stream contract as :func:`fat_sweep_insert` with presence
    (col 0 skey, 1..W masks, W+1 original index + 1, sentinel tail
    padding); returns ONLY the ``uint32[P*KJC, 128]`` presence slot
    tiles (``idx+1 | hit << 31`` per slot — the
    :func:`_fat_unsort_presence` layout). ``blocks_fat`` is read-only:
    no aliasing, no donation — a query step never invalidates the
    array a concurrent launch may also be reading."""
    NB8, L = blocks_fat.shape
    assert L == 128
    P8 = NB8 // R8
    P = P8 // S
    kjc = pack * _packed_rows(KJ, pack)
    kbjp = _packed_rows(KBJ, pack)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(P,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((S * R8, 128), lambda p, *_: (p, 0)),
        ],
        out_specs=pl.BlockSpec((kjc, 128), lambda p, *_: (p, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, J, kbjp, 128), jnp.uint32),
            pltpu.SemaphoreType.DMA((2, J)),
        ],
    )
    fn = pl.pallas_call(
        functools.partial(
            _fat_query_kernel,
            R8=R8, S=S, KJ=KJ, KBJ=KBJ, P8=P8, W=W, J=J, NBJ=NB8,
            PACK=pack,
        ),
        out_shape=jax.ShapeDtypeStruct((P * kjc, 128), jnp.uint32),
        grid_spec=grid_spec,
        interpret=interpret,
    )
    return fn(starts, upd, blocks_fat)


def choose_fat_query_params(nb: int, batch: int, words_per_block: int = 16):
    """(J, R8, S, KJ, KBJ) for the read-only query sweep, or None.

    The query chooser entry (ISSUE 12): windows run 6-sigma slack like
    presence windows (overflow falls back to the gather query, which is
    also the universal fallback path), lambda prefers the LARGEST
    feasible value (the kernel is per-window-overhead-bound and a pure
    query has even less per-window arithmetic to amortize than the
    fused kernel — RESULTS_r5 §2/§2b), and the scoped-VMEM estimate
    drops the fused kernel's output-tile and delta terms, which is what
    lets query geometries run larger lambda at equal footprint. The
    bodies/volume caps start at the presence kernel's measured envelope
    (the query body is a strict subset of the presence body's scoped
    surfaces, so every shape the presence caps admit is safe here);
    shapes beyond it are admitted solely by the probe compile — ground
    truth on hardware, cached per process and per device kind on disk
    (the PR-11 machinery)."""
    import math

    w = words_per_block
    if 1 + w + 1 > 128:
        # stream row holds skey + W mask words + key idx in 128 lanes
        return None
    J = 128 // w
    if J < 1 or w * J != 128 or nb % J:
        return None
    NBJ = nb // J
    cap = 1024
    candidates = []
    for r8 in (32, 64, 128, 256, 512, 1024):
        if r8 > NBJ or NBJ % r8:
            continue
        lam = batch * r8 // nb
        if lam < 8:
            # the sweep streams the WHOLE array per call — a sparse
            # batch pays the full stream for a handful of rows (same
            # break-even guard as the insert choosers)
            continue
        candidates.append((-lam, r8, lam))
    for _, R8, lam in sorted(candidates):
        kj_raw = max(
            16, (lam + max(16, int(6 * math.sqrt(lam))) + 7) // 8 * 8
        )
        if kj_raw > 1024:
            continue
        KJ = kj_raw
        P8 = NBJ // R8
        for s in (8, 4, 2, 1):
            if P8 % s or s * R8 > cap or P8 // s < 2:
                continue
            pk = fat_pack(w, True)  # stream carries the idx column
            bodies = s * J * pk
            # presence-kernel caps as the floor envelope (see docstring);
            # the joint rule mirrors choose_fat_params' presence matrix
            if bodies > 128:
                continue
            volume = bodies * _packed_rows(KJ, pk) * R8
            cap_v = 3_500_000 if bodies <= 64 else 2_200_000
            if volume > cap_v:
                continue
            kbj = ((lam * s + KJ + 64 + 7) // 8) * 8
            sup_rows = _packed_rows(kbj, pk)
            kjc = pk * _packed_rows(KJ, pk)
            # scoped-VMEM estimate: double-buffered window fetches + the
            # read-only block tile + the presence tile — the fused
            # kernel's 4x (in+out tile) term shrinks to in-tile + pres
            if (
                2 * J * sup_rows * 128 * 4
                + 2 * (s * R8 * 128 * 4)
                + kjc * 128 * 4
                <= 9 * 1024 * 1024
            ):
                geom = (J, R8, s, KJ, kbj)
                if not _fat_geometry_compiles(
                    nb, w, geom, presence=False, counting=False,
                    query=True, batch=batch,
                ):
                    continue
                return geom
    return None


def auto_query_path(
    backend: str, n_blocks: int, batch: int, words_per_block: int = 16
) -> str:
    """The implementation ``query_path="auto"`` resolves to — the single
    source of truth shared by :func:`tpubloom.filter.make_blocked_query_fn`,
    the sharded per-device query loop, and the benchmarks' metadata. The
    Mosaic kernel only lowers on TPU; every other backend takes the
    gather path."""
    if backend == "tpu" and choose_fat_query_params(
        n_blocks, batch, words_per_block
    ) is not None:
        return "sweep"
    return "gather"


def resolve_query_path(
    config, batch: int, backend: str | None = None, *,
    n_blocks: int | None = None,
) -> str:
    """Resolve ``config.query_path`` ("auto"/"sweep"/"gather") for a
    batch size on the current (or given) backend — the ONE funnel for
    every blocked-membership path decision (single-chip, packed, and —
    via ``n_blocks``, which the sharded per-device loop uses to pass
    its LOCAL row count — the shard_map path)."""
    qp = getattr(config, "query_path", "auto")
    if qp != "auto":
        return qp
    if backend is None:
        backend = jax.default_backend()
    return auto_query_path(
        backend,
        config.n_blocks if n_blocks is None else n_blocks,
        batch,
        config.words_per_block,
    )


def effective_query_path(
    config, batch: int, backend: str | None = None, *,
    n_blocks: int | None = None,
) -> str:
    """:func:`resolve_query_path` with applicability folded in — what
    actually LAUNCHES. A forced ``query_path="sweep"`` on a shape the
    kernel cannot take (tiny batch below the lambda floor, odd
    geometry, every candidate probe-demoted) answers "gather" instead
    of erroring: queries are bit-identical on either path, so unlike a
    forced insert sweep there is no silent-wrong-result risk a hard
    error would protect against — and a served filter sees arbitrary
    request sizes, where erroring on small batches would make the knob
    unusable. The ``query_gather_launches`` counter reports the
    demotion. Callers that want the raw kernel contract (tests, the
    probes) use :func:`make_sweep_query_fn` directly, which still
    raises on unsupported shapes."""
    if backend is None:
        backend = jax.default_backend()
    return _effective_query_path_cached(
        getattr(config, "query_path", "auto"),
        config.n_blocks if n_blocks is None else n_blocks,
        config.words_per_block,
        batch,
        backend,
    )


@functools.lru_cache(maxsize=512)
def _effective_query_path_cached(
    query_path: str, n_blocks: int, words_per_block: int, batch: int,
    backend: str,
) -> str:
    """One chooser pass per distinct decision input, memoized — the
    launch-mix counter calls this per query launch, and the chooser's
    candidate scan (plus probe-cache lookups on TPU) is pure in these
    five values for the life of the process (probe results only ever
    warm monotonically, and the first chooser call settles them)."""
    if query_path == "gather":
        return "gather"
    if query_path == "auto" and backend != "tpu":
        return "gather"
    if choose_fat_query_params(n_blocks, batch, words_per_block) is None:
        return "gather"
    return "sweep"


def apply_fat_query(
    blocks: jnp.ndarray,
    blk: jnp.ndarray,
    bit: jnp.ndarray,
    valid: jnp.ndarray,
    *,
    block_bits: int,
    params,
    interpret: bool | None = None,
    storage_fat: bool = False,
) -> jnp.ndarray:
    """Membership of each valid key via the read-only query sweep;
    ``params`` from :func:`choose_fat_query_params`. Returns ``bool[B]``
    (False at invalid entries). ``blocks`` is NEVER modified.

    Contract (same as the fused presence path): invalid entries
    (``valid`` False) must form a TAIL SUFFIX of the batch — they emit
    no presence slot, so a mid-batch invalid entry would shift every
    later key's verdict in the index-sorted unsort.
    ``tpubloom.filter._pack_padded`` guarantees tail padding; the
    sharded per-device loop passes ``lengths >= 0`` (NOT ``owned``) for
    exactly this reason and masks unowned verdicts after the psum.

    Windows that overflow their KJ fetch (adversarial duplicate skew)
    route the WHOLE batch to the gather query under ``lax.cond`` — the
    same correctness-safe fallback design as :func:`apply_fat_updates`.
    """
    w = block_bits // 32
    J0, R8, S, KJ, KBJ = params
    nb = blocks.size // w
    B = blk.shape[0]
    J = J0
    NBJ = nb // J
    P8 = NBJ // R8
    interp = jax.default_backend() == "cpu" if interpret is None else interpret
    blkv = jnp.where(valid, blk, nb)
    j_of = (blkv % J).astype(jnp.uint32)
    rf_of = (blkv // J).astype(jnp.uint32)
    skey = jnp.where(valid, j_of * NBJ + rf_of, _u32(J * NBJ))
    cols, nbits, packed = _pack_positions(bit, block_bits, bit.shape[-1])
    idx0 = jnp.arange(1, B + 1, dtype=jnp.uint32)  # 0 = empty slot
    sorted_cols = lax.sort((skey,) + cols + (idx0,), num_keys=1)
    ss = sorted_cols[0]
    bit_sorted = _unpack_positions(
        sorted_cols[1:-1], block_bits, bit.shape[-1], nbits, packed
    )
    masks = blocked.build_masks(bit_sorted, w)
    idx_sorted = sorted_cols[-1]
    pack = fat_pack(w, True)
    upd, starts = _fat_stream(
        ss, masks, idx_sorted, J=J, NBJ=NBJ, P8=P8, R8=R8, KBJ=KBJ, W=w,
        pack=pack,
    )
    overflow = _fat_window_overflow(
        starts, J=J, P8=P8, S=S, KJ=KJ, KBJ=KBJ, pack=pack
    )

    def sweep_branch(ops):
        bl, u, st = ops
        presb = fat_sweep_query(
            bl if storage_fat else bl.reshape(NBJ, 128), u, st,
            J=J, R8=R8, S=S, KJ=KJ, KBJ=KBJ, W=w, interpret=interp,
            pack=pack,
        )
        return _fat_unsort_presence(
            presb, st, B, J=J, NBJ=NBJ, P8=P8, R8=R8, S=S,
            KJ=pack * _packed_rows(KJ, pack), KBJ=KBJ,
        )

    def gather_branch(ops):
        bl, u, st = ops
        masks_orig = blocked.build_masks(bit, w)
        if storage_fat:
            hit = blocked.fat_blocked_query(bl, blk, masks_orig)
        else:
            rows = bl[jnp.minimum(jnp.where(valid, blk, 0), nb - 1)]
            hit = jnp.all((rows & masks_orig) == masks_orig, axis=-1)
        return hit & valid

    return lax.cond(overflow, gather_branch, sweep_branch, (blocks, upd, starts))


def make_sweep_query_fn(
    config, *, interpret: bool | None = None, storage_fat: bool = False,
):
    """Pure ``(blocks, keys_u8, lengths) -> bool[B]`` blocked membership
    via the read-only query sweep. Bit-identical verdicts to
    :func:`tpubloom.filter.make_blocked_query_fn`'s gather path (same
    blocked position spec; the CPU oracle is the shared ground truth).

    ``storage_fat``: blocks are the fat [NB/J, 128] view (the
    persistent-filter layout — no reshape at the kernel boundary).
    Requires batch padding (lengths < 0) at the TAIL of the batch
    (tpubloom.filter._pack_padded guarantees this); padded entries
    return False.
    """
    nb, bb, w = config.n_blocks, config.block_bits, config.words_per_block
    k, seed, bh = config.k, config.seed, config.block_hash

    def query(blocks, keys_u8, lengths):
        B = keys_u8.shape[0]
        params = choose_fat_query_params(nb, B, w)
        if params is None:
            raise ValueError(
                f"sweep query does not support this shape (n_blocks={nb}, "
                f"batch={B}, words_per_block={w}) — use query_path='gather'"
            )
        valid = lengths >= 0
        blk, bit = blocked.block_positions(
            keys_u8, jnp.maximum(lengths, 0),
            n_blocks=nb, block_bits=bb, k=k, seed=seed, block_hash=bh,
        )
        return apply_fat_query(
            blocks, blk, bit, valid,
            block_bits=bb, params=params, interpret=interpret,
            storage_fat=storage_fat,
        )

    return query
