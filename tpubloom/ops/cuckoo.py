"""TPU-native cuckoo-filter kernels (ISSUE 19).

A cuckoo filter (Fan et al., CoNEXT'14) stores short *fingerprints* in
small buckets and resolves collisions by relocating ("kicking") resident
fingerprints to their alternate bucket. Compared to the counting bloom
filter it supports true deletion without 4-bit counters and beats bloom
space below ~3% FPR; the cost is that inserts can fail (FULL) when the
table is loaded — which this implementation reports *honestly* instead
of silently dropping keys.

Layout and spec
---------------

* Storage is ``uint32[n_buckets, BUCKET_SIZE]`` — one 16-bit fingerprint
  per uint32 lane (the top 16 bits stay zero; lane-native uint32 keeps
  the scatter/gather paths on the same fast path as the bloom word
  arrays). ``0`` means "empty slot"; fingerprints live in [1, 0xFFFF].
* ``fp = (h_a mod 0xFFFF) + 1`` and ``i1 = h_b & (n_buckets-1)`` come
  from the shared MurmurHash3 family in :mod:`tpubloom.ops.hashing` —
  the same ``base_hashes`` every other kind derives positions from.
* Partial-key cuckooing: ``i2 = i1 XOR (mix(fp) & mask)`` with a
  multiplicative mix, so the alternate bucket is computable from
  (bucket, fingerprint) alone — required for kicking, where the original
  key is long gone.

Why a scan + fixed-trip loop
----------------------------

Inserts are a ``lax.scan`` over the batch (relocation makes inserts
order-dependent; a parallel scatter would race on bucket occupancy) and
the kick chain inside each step is a *fixed-trip* ``lax.fori_loop`` of
``MAX_KICKS`` iterations with per-lane ``done`` masking — data-dependent
``while_loop`` trip counts don't lower to TPU, and a bounded loop is
exactly the honest-FULL semantics anyway. A failed chain **unwinds**:
the loop records the (bucket, slot) eviction path and a second
fixed-trip loop walks it backwards restoring every displaced
fingerprint, so a FULL insert leaves the table bit-identical to before
it started (no collateral eviction of other keys' fingerprints).

Inserts have *multiset* semantics (a duplicate add stores a second copy,
as RedisBloom's ``CF.ADD`` does) — which is precisely why cuckoo inserts
and deletes are classified replay-UNSAFE in the kind registry and ride
the rid-dedup cache.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from tpubloom.ops import hashing

#: Fingerprints per bucket. 4 is the classic sweet spot: ~95% load factor
#: with 2 candidate buckets before FULL sets in.
BUCKET_SIZE = 4

#: Kick-chain bound. 32 relocations on a b=4 table is past the point
#: where success probability matters — a chain this long means the table
#: is effectively full, so we report FULL rather than thrash.
MAX_KICKS = 32

_ALT_MIX = jnp.uint32(0x5BD1E995)  # MurmurHash2 multiplicative constant


def derive(keys, lengths, *, n_buckets: int, seed: int):
    """Fingerprint + primary bucket for each key.

    Args:
      keys: uint8[..., L] zero-padded keys (see hashing.murmur3_32).
      lengths: int32[...] true byte lengths.
      n_buckets: power-of-two bucket count.
      seed: u32 hash seed (the filter's identity seed).

    Returns:
      (fp, i1): uint32[...] fingerprint in [1, 0xFFFF] and primary bucket.
    """
    h_a, h_b, _, _ = hashing.base_hashes(keys, lengths, seed)
    fp = (h_a % jnp.uint32(0xFFFF)) + jnp.uint32(1)
    i1 = h_b & jnp.uint32(n_buckets - 1)
    return fp, i1


def alt_bucket(bucket, fp, mask):
    """Alternate bucket: i XOR (mix(fp) & mask) — an involution, so it
    maps i1->i2 and i2->i1 given only the stored fingerprint."""
    return (bucket ^ (fp * _ALT_MIX)) & mask


def _place_if(slots, bucket, fp, do):
    """Store ``fp`` in the first empty slot of ``bucket`` when ``do`` and
    one exists; returns (slots, placed)."""
    row = slots[bucket]
    empty = row == 0
    placed = empty.any() & do
    row2 = row.at[jnp.argmax(empty)].set(fp)
    return slots.at[bucket].set(jnp.where(placed, row2, row)), placed


@jax.jit
def cuckoo_insert(slots, fp, i1, valid):
    """Insert a batch of fingerprints; honest-FULL with chain unwind.

    Args:
      slots: uint32[n_buckets, BUCKET_SIZE] table (n_buckets pow2).
      fp, i1: uint32[B] from :func:`derive`.
      valid: bool[B] lane mask (False lanes are no-ops reporting ok=False).

    Returns:
      (slots, ok, kicks): updated table, bool[B] per-key success
      (False == FULL for valid lanes), int32[B] relocations performed
      (a FULL lane still reports its MAX_KICKS attempted-and-unwound).
    """
    mask = jnp.uint32(slots.shape[0] - 1)

    def insert_one(slots, xs):
        f, b1, v = xs
        b2 = alt_bucket(b1, f, mask)
        slots, ok1 = _place_if(slots, b1, f, v)
        slots, ok2 = _place_if(slots, b2, f, v & ~ok1)
        done0 = ok1 | ok2 | ~v

        path_b = jnp.zeros((MAX_KICKS,), jnp.uint32)
        path_s = jnp.zeros((MAX_KICKS,), jnp.int32)

        def kick(t, carry):
            slots, f, b, done, path_b, path_s, nk = carry
            s = ((f + jnp.uint32(t)) % jnp.uint32(BUCKET_SIZE)).astype(jnp.int32)
            victim = slots[b, s]
            slots = slots.at[b, s].set(jnp.where(done, victim, f))
            path_b = path_b.at[t].set(b)
            path_s = path_s.at[t].set(s)
            nk = nk + jnp.where(done, jnp.int32(0), jnp.int32(1))
            nb = alt_bucket(b, victim, mask)
            slots, placed = _place_if(slots, nb, victim, ~done)
            return (
                slots,
                jnp.where(done, f, victim),
                jnp.where(done, b, nb),
                done | placed,
                path_b,
                path_s,
                nk,
            )

        slots, f_end, _, done, path_b, path_s, nk = lax.fori_loop(
            0, MAX_KICKS, kick,
            (slots, f, b2, done0, path_b, path_s, jnp.int32(0)),
        )

        # FULL: walk the eviction path backwards, un-displacing every
        # fingerprint the chain moved, so the table is exactly restored.
        fail = ~done

        def unwind(i, carry):
            slots, held = carry
            t = jnp.maximum(nk - 1 - i, 0)
            b, s = path_b[t], path_s[t]
            cur = slots[b, s]
            do = fail & (i < nk)
            slots = slots.at[b, s].set(jnp.where(do, held, cur))
            return slots, jnp.where(do, cur, held)

        slots, _ = lax.fori_loop(0, MAX_KICKS, unwind, (slots, f_end))
        return slots, (done & v, nk)

    slots, (ok, kicks) = lax.scan(insert_one, slots, (fp, i1, valid))
    return slots, ok, kicks


@jax.jit
def cuckoo_query(slots, fp, i1, valid):
    """Membership: fingerprint present in either candidate bucket.
    Fully vectorized (reads don't race); returns bool[B]."""
    mask = jnp.uint32(slots.shape[0] - 1)
    b2 = alt_bucket(i1, fp, mask)
    f = fp[:, None]
    hit1 = (slots[i1] == f).any(axis=-1)
    hit2 = (slots[b2] == f).any(axis=-1)
    return (hit1 | hit2) & valid


@jax.jit
def cuckoo_delete(slots, fp, i1, valid):
    """Delete ONE stored copy of each key's fingerprint (multiset pop).

    Sequential scan so intra-batch duplicate deletes each consume their
    own copy. Returns (slots, deleted: bool[B]); a False lane means the
    fingerprint wasn't present (delete of a never-added key — which, as
    with every cuckoo filter, must not happen for membership integrity
    and is surfaced to the caller instead of being masked).
    """
    mask = jnp.uint32(slots.shape[0] - 1)

    def _remove_if(slots, bucket, f, do):
        row = slots[bucket]
        match = row == f
        hit = match.any() & do
        row2 = row.at[jnp.argmax(match)].set(jnp.uint32(0))
        return slots.at[bucket].set(jnp.where(hit, row2, row)), hit

    def delete_one(slots, xs):
        f, b1, v = xs
        b2 = alt_bucket(b1, f, mask)
        slots, d1 = _remove_if(slots, b1, f, v)
        slots, d2 = _remove_if(slots, b2, f, v & ~d1)
        return slots, d1 | d2

    slots, deleted = lax.scan(delete_one, slots, (fp, i1, valid))
    return slots, deleted


@jax.jit
def occupancy(slots):
    """Occupied slot count (for fill/stats)."""
    return (slots != 0).sum(dtype=jnp.int32)
