"""Blocked (cache-line) bloom-filter kernels — the throughput layout.

Why this exists: the flat spec (tpubloom.ops.hashing) scatters each key's
k bits uniformly over the whole m-bit array — k random 4-byte HBM
accesses per key. TPU HBM serves random traffic at sector granularity
(~512 B), so the flat hot path is latency-bound at roughly
``k × (random access rate)``. The blocked layout (Putze, Sanders &
Singler 2007, "Cache-, Hash- and Space-Efficient Bloom Filters")
confines all k bits of a key to ONE ``block_bits``-sized block:

* one contiguous 64–512 B row gather per query (vs k scattered reads),
* one row read-modify-write per insert (vs k scattered RMWs),

i.e. ~k× less random HBM traffic, which measured ~10× faster end-to-end
on v5e at m=2^32, k=7. The price is a slightly higher FPR at high fill
(block loads are Poisson-skewed); at the north-star operating point
(fill ≈ 6%) the excess is negligible. See BloomFilter docstrings for the
user-facing guidance.

THE BLOCKED POSITION SPEC (canonical; CPU oracle + tests mirror it)
-------------------------------------------------------------------
Given the four base hashes of the flat spec (h_a, h_b, g_a, g_b — see
tpubloom.ops.hashing), ``n_blocks = m / block_bits`` (both powers of 2):

  blk = h_a mod n_blocks                          # owning block

and, with ``b`` the in-block position count (= block_bits here; the
blocked COUNTING layout reuses this function with b = counters per
block), TWO in-block variants selected by ``config.block_hash``:

``"chunk"`` (default where it fits — see config.FilterConfig):

  pool    = h_b | g_a<<32 | g_b<<64                # 96-bit hash pool
  bit_i   = (pool >> (i·log2(b))) mod b,  i = 0..k-1

i.e. each position reads a disjoint log2(b)-bit slice of the pool —
positions are i.i.d. uniform. Requires k·log2(b) <= 96.

``"ap"`` (legacy):

  p_i     = (g_a + i·(g_b | 1)) mod 2^32,  i = 0..k-1
  bit_i   = p_i mod b

The AP variant's position SET is determined by just
(g_a mod b, g_b mod b) — a 2-parameter family of arithmetic
progressions. Two same-block keys colliding in those ~2·log2(b) bits
share every position, which floors the filter's FPR at ~4·load/b²
regardless of fill (measured: 1.6e-4 at the north-star shape where
theory says 1e-6 — see params.blocked_fpr and tests/test_fpr_model.py).
"chunk" removes that floor; "ap" remains supported to restore
checkpoints written before the field existed.

Bit ``bit_i`` of a block is bit ``bit_i mod 32`` (LSB-first) of word
``bit_i div 32`` in the block's ``uint32[block_bits/32]`` row. Blocked
arrays are therefore NOT bit-compatible with flat arrays; the layout is
part of the filter's identity (config.block_bits, config.block_hash).

AP in-block positions cannot collide within a key when b is a power of
two (odd stride), chunk positions can (i.i.d.) — standard bloom
behavior; the FPR model accounts for both.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from tpubloom.ops import hashing
from tpubloom.ops.bitops import segmented_scan_last


def _u32(x) -> jnp.ndarray:
    return jnp.asarray(x, jnp.uint32)


def block_positions(
    keys: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    n_blocks: int,
    block_bits: int,
    k: int,
    seed: int,
    block_hash: str = "ap",
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Blocked-spec coordinates of each key (module docstring has the spec).

    Returns ``(blk, bit)``: ``blk`` int32[...], owning block per key;
    ``bit`` uint32[..., k], in-block bit positions. ``block_hash`` selects
    the in-block variant ("chunk" / "ap"); callers with a FilterConfig
    must pass ``config.block_hash`` — it is part of the filter identity.
    """
    h_a = hashing.murmur3_32(keys, lengths, seed)
    g_a = hashing.fnv1a_32(keys, lengths)
    g_b = hashing.murmur3_32(keys, lengths, seed ^ hashing.SEED_XOR_GB)
    blk = (h_a & _u32(n_blocks - 1)).astype(jnp.int32)
    mask = _u32(block_bits - 1)
    bits = []
    if block_hash == "chunk":
        nb = (block_bits - 1).bit_length()
        if k * nb > 96:
            raise ValueError(
                f"chunk in-block hash needs k*log2(block_bits) <= 96 "
                f"(k={k}, {nb} bits/position)"
            )
        h_b = hashing.murmur3_32(keys, lengths, seed ^ hashing.SEED_XOR_HB)
        pool = (h_b, g_a, g_b)
        for i in range(k):
            sh = i * nb
            w, off = sh >> 5, sh & 31
            v = pool[w] >> _u32(off)
            if off + nb > 32:
                v = v | (pool[w + 1] << _u32(32 - off))
            bits.append(v & mask)
    elif block_hash == "ap":
        stride = g_b | _u32(1)
        p = g_a
        for i in range(k):
            if i > 0:
                p = p + stride  # u32 wrap == mod 2^32
            bits.append(p & mask)
    else:
        raise ValueError(f"block_hash must be 'chunk' or 'ap', got {block_hash!r}")
    return blk, jnp.stack(bits, axis=-1)


def build_masks(bit: jnp.ndarray, words_per_block: int) -> jnp.ndarray:
    """OR the k in-block positions into per-key row masks.

    ``bit``: uint32[B, k] in-block positions -> uint32[B, W] row masks,
    W = words_per_block. Dense VPU work: B×k×W compares, no gathers.
    """
    word = (bit >> _u32(5)).astype(jnp.int32)  # [B, k] in [0, W)
    one = _u32(1) << (bit & _u32(31))  # [B, k]
    iota = lax.broadcasted_iota(jnp.int32, (1, words_per_block), 1)  # [1, W]
    k = bit.shape[-1]
    mask = jnp.zeros(bit.shape[:-1] + (words_per_block,), jnp.uint32)
    for i in range(k):  # k is static and small; OR-accumulate one-hot words
        mask = mask | jnp.where(
            word[..., i, None] == iota, one[..., i, None], _u32(0)
        )
    return mask  # [B, W]


def _replicate_masks_128(masks: jnp.ndarray) -> jnp.ndarray:
    """[B, W] u32 -> [B, 128] with the mask repeated in every lane group,
    via 4 exact byte-quarter matmuls against a constant [W, 128] 0/1
    weight (byte values <= 255 are bf16-exact; f32 accumulation).

    Why a matmul: a [B, W] array is ALREADY 128-lane padded in TPU
    layout, so every lane-space alternative is a real cross-row
    relayout at B=4M — ``concatenate([masks]*J, axis=1)`` costs ~47 ms
    (benchmarks/out/query_probe_r5.json q3) and static lane slices of a
    [B, 128] operand cost ~20 ms EACH (benchmarks/out/query_fix_r5.json
    variant A, ~126 ms over the fold for J=8 slices). The MXU
    replicates across lanes for free: measured 106 ms vs 232 ms (slices)
    vs 114 ms (concat) for the full query step at B=4M."""
    B, w = masks.shape
    iw = lax.broadcasted_iota(jnp.int32, (w, 128), 0)
    il = lax.broadcasted_iota(jnp.int32, (w, 128), 1)
    sel = (il % w == iw).astype(jnp.bfloat16)  # [W, 128] 0/1
    out = jnp.zeros((B, 128), jnp.uint32)
    for b in range(4):
        q = ((masks >> _u32(8 * b)) & _u32(0xFF)).astype(jnp.bfloat16)
        rep = lax.dot_general(
            q, sel, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        out = out | (rep.astype(jnp.uint32) << _u32(8 * b))
    return out


def fat_fold_masks(
    blk: jnp.ndarray, masks: jnp.ndarray, J: int
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Translate (block id, [B, W] mask) pairs to the fat [NB/J, 128]
    view: returns ``(fat_row[B], masks128[B, 128])`` with each mask
    placed at lane group ``blk % J``. Lets the scatter/gather fallbacks
    operate on fat storage DIRECTLY — a [NB, W] <-> fat reshape is a
    real ~26 ms copy at m=2^32 on TPU (benchmarks/RESULTS_r3.md §2),
    while this fold is 4 constant-weight matmuls + one select (see
    :func:`_replicate_masks_128` for why NOT lane concat or slices).
    ``blocked_insert``/``blocked_query`` accept the folded pair
    unchanged (they are generic over row width; distinct blocks sharing
    a fat row merge by OR at disjoint lanes).
    """
    B, w = masks.shape
    lane = lax.broadcasted_iota(jnp.int32, (B, 128), 1)
    sel = (lane // w) == (blk % J).astype(jnp.int32)[:, None]
    return (blk // J).astype(jnp.int32), jnp.where(
        sel, _replicate_masks_128(masks), _u32(0)
    )


def blocked_insert(
    blocks: jnp.ndarray, blk: jnp.ndarray, masks: jnp.ndarray, valid: jnp.ndarray
) -> jnp.ndarray:
    """OR each key's mask row into its block. Duplicate blocks within the
    batch are merged by a sort + segmented row-OR so the final row scatter
    has unique indices (same recipe as bitops.scatter_or, at row granularity
    — 1 sort of B elements instead of B·k).

    ``valid == False`` entries (batch padding) are redirected out of bounds
    and dropped by the scatter.
    """
    n_blocks = blocks.shape[0]
    b = jnp.where(valid, blk, n_blocks).astype(jnp.int32)
    order = jnp.argsort(b)
    bs = b[order]
    rows, is_last = segmented_scan_last(bs, masks[order], jnp.bitwise_or)
    target = jnp.where(is_last & (bs < n_blocks), bs, n_blocks)
    current = blocks[jnp.minimum(bs, n_blocks - 1)]
    merged = current | rows
    return blocks.at[target].set(merged, mode="drop", unique_indices=True)


def fat_blocked_query(
    blocks_fat: jnp.ndarray, blk: jnp.ndarray, masks: jnp.ndarray
) -> jnp.ndarray:
    """Membership against the fat [NB/J, 128] view: gather each key's fat
    row, fold the mask to the owning lane group with the matmul
    replication (:func:`_replicate_masks_128`), one full-width compare.

    Every lane-space alternative measured slower at B=4M
    (benchmarks/out/query_fix_r5.json): J static-slice compares 232 ms
    (each slice is a hidden cross-lane relayout), lane-concat fold
    114 ms, this path 106 ms against a 70 ms gather-only floor.
    take_along_axis / multi-index lax.gather scalarize outright
    (measured r4: 9x and 54x collapses)."""
    w = masks.shape[-1]
    J = 128 // w
    frow, m128 = fat_fold_masks(blk, masks, J)
    rows128 = blocks_fat[frow]  # [B, 128] row gather
    return jnp.all((rows128 & m128) == m128, axis=-1)


def blocked_query(
    blocks: jnp.ndarray, blk: jnp.ndarray, masks: jnp.ndarray
) -> jnp.ndarray:
    """Membership: one row gather per key + all-mask-bits-present test.

    Padded entries carry the empty-key verdict (length is clamped to 0
    upstream, so their masks are the hash of ``b""``, not zeros) — callers
    must trim the batch (include_batch) or mask the result (sharded
    ``owned``); the values at padded positions are meaningless.
    """
    rows = blocks[blk]
    return jnp.all((rows & masks) == masks, axis=-1)
