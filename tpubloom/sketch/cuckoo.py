"""CuckooFilter — deletable membership without 4-bit counters (ISSUE 19).

Front-end class over :mod:`tpubloom.ops.cuckoo`. Storage is the flat
``uint32[m]`` slot array (m = ``config.m`` fingerprint slots, power of
two; viewed as ``[m/BUCKET_SIZE, BUCKET_SIZE]`` buckets in-kernel), so
the checkpoint / replication / migration planes move it exactly like
every other kind's flat word array.

Semantic differences from the bloom family, surfaced honestly:

* ``insert_batch`` can FAIL per key (table full after ``MAX_KICKS``
  relocations). The per-key verdicts are staged device-side and fetched
  by :meth:`take_insert_flags` — the service / coalescer call it after
  the kernel fence and ship a ``full`` bitmap in the response instead of
  silently dropping keys.
* inserts are multiset (duplicate adds store extra copies), so inserts
  AND deletes are replay-unsafe — the kind registry classifies them for
  the rid-dedup cache.
* ``delete_batch`` removes ONE stored copy per key and reports per-key
  whether a copy existed. Deleting a never-inserted key is a contract
  violation (it may evict another key's fingerprint) — same rule as
  every cuckoo filter; the flags let callers detect it.
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np

from tpubloom import faults
from tpubloom.config import FilterConfig
from tpubloom.filter import _FilterBase
from tpubloom.obs import context as obs
from tpubloom.obs import counters as obs_counters
from tpubloom.ops import cuckoo as ops_cuckoo


class CuckooFilter(_FilterBase):
    """Bucketed-fingerprint cuckoo filter on a flat uint32 device array."""

    def __init__(self, config: FilterConfig):
        if config.kind != "cuckoo":
            raise ValueError(f"CuckooFilter needs kind='cuckoo', got {config.kind!r}")
        if config.m < ops_cuckoo.BUCKET_SIZE * 2:
            raise ValueError(
                f"cuckoo needs at least 2 buckets ({2 * ops_cuckoo.BUCKET_SIZE} "
                f"slots), got m={config.m}"
            )
        super().__init__(config, config.m)
        n_buckets = config.m // ops_cuckoo.BUCKET_SIZE
        self.n_buckets = n_buckets
        seed = config.seed
        shape = (n_buckets, ops_cuckoo.BUCKET_SIZE)

        def _derive(keys_u8, lengths):
            return ops_cuckoo.derive(
                keys_u8, lengths, n_buckets=n_buckets, seed=seed
            )

        def _ins(words, keys_u8, lengths):
            valid = lengths >= 0
            fp, i1 = _derive(keys_u8, lengths)
            slots, ok, kicks = ops_cuckoo.cuckoo_insert(
                words.reshape(shape), fp, i1, valid
            )
            return slots.reshape(-1), ok, kicks.sum()

        def _qry(words, keys_u8, lengths):
            valid = lengths >= 0
            fp, i1 = _derive(keys_u8, lengths)
            return ops_cuckoo.cuckoo_query(words.reshape(shape), fp, i1, valid)

        def _del(words, keys_u8, lengths):
            valid = lengths >= 0
            fp, i1 = _derive(keys_u8, lengths)
            slots, deleted = ops_cuckoo.cuckoo_delete(
                words.reshape(shape), fp, i1, valid
            )
            return slots.reshape(-1), deleted

        self._insert_full = jax.jit(_ins, donate_argnums=0)
        self._query = jax.jit(_qry)
        self._delete = jax.jit(_del, donate_argnums=0)
        #: (device ok flags, true batch size, device kick count) of the
        #: last insert, until take_insert_flags() collects it.
        self._pending_flags = None

    # -- insert (every path funnels through launch_insert so the FULL
    # verdicts are never lost, whichever plane drove the batch) ----------

    def launch_insert(self, staged):
        d_keys, d_lengths, B = staged
        faults.fire("cuckoo.kick", filter=self.config.key_name, batch=B)
        with obs.phase("kernel"):
            self.words, ok, kicks = self._insert_full(self.words, d_keys, d_lengths)
        self._pending_flags = (ok, B, kicks)
        self.n_inserted += B
        return self.words

    def insert_batch(self, keys: Sequence[bytes | str]) -> None:
        out = self.launch_insert(self.stage_batch(keys))
        if obs.current() is not None:
            with obs.phase("kernel"):
                self._kernel_fence(out)

    def insert_arrays(self, keys_u8, lengths, *, n_valid=None) -> None:
        faults.fire("cuckoo.kick", filter=self.config.key_name)
        self.words, ok, kicks = self._insert_full(self.words, keys_u8, lengths)
        B = int(keys_u8.shape[0]) if n_valid is None else n_valid
        self._pending_flags = (ok, B, kicks)
        self.n_inserted += B

    def take_insert_flags(self):
        """Per-key success flags of the LAST insert (bool[B]; False ==
        FULL), or None if already collected. Also settles the kick /
        rejection counters — metrics follow the acked batch, not the
        async launch."""
        pending = self._pending_flags
        self._pending_flags = None
        if pending is None:
            return None
        ok, B, kicks = pending
        flags = np.asarray(ok)[:B]
        nk = int(np.asarray(kicks))
        if nk:
            obs_counters.incr("cuckoo_kicks_total", nk)
        rejected = int(B - flags.sum())
        if rejected:
            obs_counters.incr("cuckoo_full_rejections", rejected)
        return flags

    # -- delete ----------------------------------------------------------

    def delete_batch(self, keys: Sequence[bytes | str]) -> np.ndarray:
        """Remove one stored copy per key; returns bool[B] per-key
        "a copy existed"."""
        keys_u8, lengths, B = self._pack_padded(keys)
        d_keys, d_lengths = self._stage_batch(keys_u8, lengths)
        with obs.phase("kernel"):
            self.words, deleted = self._delete(self.words, d_keys, d_lengths)
            if obs.current() is not None:
                self._kernel_fence(self.words)
        with obs.phase("d2h"):
            out = np.asarray(deleted)
        return out[:B]

    # -- stats / persistence hooks --------------------------------------

    def clear(self) -> None:
        super().clear()
        self._pending_flags = None

    def fill_ratio(self) -> float:
        occ = int(
            np.asarray(
                ops_cuckoo.occupancy(
                    self.words.reshape(self.n_buckets, ops_cuckoo.BUCKET_SIZE)
                )
            )
        )
        return occ / self.config.m

    def stats(self) -> dict:
        fill = self.fill_ratio()
        return {
            "kind": "cuckoo",
            "m": self.config.m,
            "n_buckets": self.n_buckets,
            "bucket_size": ops_cuckoo.BUCKET_SIZE,
            "max_kicks": ops_cuckoo.MAX_KICKS,
            "n_inserted": self.n_inserted,
            "n_queried": self.n_queried,
            "occupied_slots": int(round(fill * self.config.m)),
            "fill_ratio": fill,
        }
