"""Kind registry: the one table that makes filter kinds pluggable.

Every serving-plane decision that must vary per kind is a column here,
so "add a filter kind" is one row plus its kernels — not a grep through
service/checkpoint/ingest for special cases:

* ``factory`` — builds the in-memory filter from its ``FilterConfig``
  (``CreateFilter`` routing and checkpoint restore both dispatch
  through it, so the two can never disagree on construction).
* ``blob_format`` — the checkpoint payload tag
  (:mod:`tpubloom.checkpoint` round-trips the flat uint32 storage under
  this name; restore refuses blobs whose tag doesn't match the config's
  kind).
* ``replay_unsafe_insert`` — whether replaying an acked insert changes
  state (multiset cuckoo adds a second fingerprint copy; CMS doubles
  counts). True routes the kind's inserts through the rid-dedup cache
  exactly like counting/scalable bloom inserts, which is what makes the
  per-kind SIGKILL chaos acceptances ("neither lost nor doubled") hold.
* ``supports_delete`` — whether ``DeleteBatch``/``CFDel`` is legal
  (cuckoo: yes, without 4-bit counters; CMS/top-k: no — a count-min
  sketch cannot un-count).

The ``"bloom"`` kind is deliberately NOT a row: the pre-existing family
(plain/counting/blocked/sharded/scalable) keeps its own routing chain in
``service._create`` / ``checkpoint._build_filter``, and the helpers here
return the neutral answer (not sketch, replay-safety decided by the
bloom-family rules) for it.
"""

from __future__ import annotations

import dataclasses
import importlib
from typing import Callable

__all__ = [
    "KindSpec",
    "blob_format",
    "build",
    "is_sketch",
    "kind_of",
    "replay_unsafe_insert",
    "sketch_kinds",
    "spec",
    "supports_delete",
]


@dataclasses.dataclass(frozen=True)
class KindSpec:
    """One pluggable filter kind. ``factory`` is a ``module:Class``
    dotted path resolved lazily (the sketch classes import jax kernels;
    the registry must stay importable from config/analysis contexts)."""

    name: str
    factory: str
    blob_format: str
    replay_unsafe_insert: bool
    supports_delete: bool

    def resolve(self) -> Callable:
        module, _, attr = self.factory.partition(":")
        return getattr(importlib.import_module(module), attr)


_SPECS = {
    "cuckoo": KindSpec(
        name="cuckoo",
        factory="tpubloom.sketch.cuckoo:CuckooFilter",
        blob_format="sketch_cuckoo_le_words",
        replay_unsafe_insert=True,  # multiset adds: replay stores a 2nd copy
        supports_delete=True,
    ),
    "cms": KindSpec(
        name="cms",
        factory="tpubloom.sketch.cms:CountMinSketch",
        blob_format="sketch_cms_le_words",
        replay_unsafe_insert=True,  # replayed increment doubles counts
        supports_delete=False,
    ),
    "topk": KindSpec(
        name="topk",
        factory="tpubloom.sketch.cms:TopKSketch",
        blob_format="sketch_topk_le_words",
        replay_unsafe_insert=True,  # CMS-backed: same doubling hazard
        supports_delete=False,
    ),
}


def sketch_kinds() -> tuple:
    """Registered sketch kinds (excludes "bloom")."""
    return tuple(sorted(_SPECS))


def kind_of(config) -> str:
    """The kind of a FilterConfig or config dict ("bloom" when absent —
    every header/record written before the field existed is bloom)."""
    if isinstance(config, dict):
        return config.get("kind") or "bloom"
    return getattr(config, "kind", "bloom") or "bloom"


def is_sketch(config) -> bool:
    return kind_of(config) != "bloom"


def spec(kind: str) -> KindSpec:
    try:
        return _SPECS[kind]
    except KeyError:
        raise ValueError(
            f"unknown filter kind {kind!r} (registered: {sketch_kinds()})"
        ) from None


def build(config):
    """Construct the filter instance for a sketch-kind config."""
    return spec(kind_of(config)).resolve()(config)


def blob_format(config) -> str:
    return spec(kind_of(config)).blob_format


def replay_unsafe_insert(config) -> bool:
    """Whether this kind's inserts must ride the rid-dedup cache.
    False for "bloom" — the bloom family's own classification
    (counting/scalable/presence) applies there."""
    kind = kind_of(config)
    if kind == "bloom":
        return False
    return spec(kind).replay_unsafe_insert


def supports_delete(config) -> bool:
    """Whether DeleteBatch is legal for this kind. False for "bloom" —
    the counting-filter check applies there."""
    kind = kind_of(config)
    if kind == "bloom":
        return False
    return spec(kind).supports_delete
