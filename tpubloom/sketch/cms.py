"""CountMinSketch / TopKSketch — frequency workloads (ISSUE 19).

Front-end classes over :mod:`tpubloom.ops.cms`. Storage is the flat
``uint32[depth * width]`` counter grid (``width = config.m``, ``depth =
config.k`` — the bloom geometry fields reinterpreted, so the sizing /
hashing / checkpoint plumbing carries over unchanged). ``insert_batch``
is a unit increment (what the shared coalescer / streaming planes
drive); :meth:`increment_batch` takes per-key weights (``CMSIncrBy``)
and returns the post-update estimates; ``include_batch`` answers
"estimate > 0" so the presence machinery works unmodified.

Replayed increments DOUBLE counts — the kind registry classifies cms /
topk inserts replay-unsafe, which routes them through the rid-dedup
cache (the SIGKILL acceptance's "neither lost nor doubled").

:class:`TopKSketch` adds the heavy-hitter heap: a host-side ``{key:
estimate}`` dict of at most ``config.topk`` entries, refreshed from a
device-side estimate pass after every update batch (the CMS estimate IS
the heavy-hitter score — no second sketch). The heap rides checkpoints
through the header's extra block (:meth:`sketch_extra` /
:meth:`load_sketch_extra`), hex-encoded because headers are JSON.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from tpubloom import faults
from tpubloom.config import FilterConfig
from tpubloom.filter import _FilterBase
from tpubloom.obs import context as obs
from tpubloom.obs import counters as obs_counters
from tpubloom.ops import cms as ops_cms


class CountMinSketch(_FilterBase):
    """[depth, width] count-min grid on a flat uint32 device array."""

    KINDS = ("cms",)

    def __init__(self, config: FilterConfig):
        if config.kind not in self.KINDS:
            raise ValueError(
                f"{type(self).__name__} needs kind in {self.KINDS}, got {config.kind!r}"
            )
        width, depth, seed = config.m, config.k, config.seed
        super().__init__(config, width * depth)
        self.width = width
        self.depth = depth

        def _pos(keys_u8, lengths):
            return ops_cms.cms_positions(
                keys_u8, lengths, width=width, depth=depth, seed=seed
            )

        def _ins(words, keys_u8, lengths):
            valid = lengths >= 0
            ones = jnp.ones(lengths.shape, jnp.uint32)
            return ops_cms.cms_update(words, _pos(keys_u8, lengths), valid, ones)

        def _qry(words, keys_u8, lengths):
            valid = lengths >= 0
            est = ops_cms.cms_estimate(words, _pos(keys_u8, lengths), valid)
            return est > 0

        def _incr(words, keys_u8, lengths, incs):
            valid = lengths >= 0
            return ops_cms.cms_update(words, _pos(keys_u8, lengths), valid, incs)

        def _est(words, keys_u8, lengths):
            valid = lengths >= 0
            return ops_cms.cms_estimate(words, _pos(keys_u8, lengths), valid)

        self._insert = jax.jit(_ins, donate_argnums=0)
        self._query = jax.jit(_qry)
        self._incr = jax.jit(_incr, donate_argnums=0)
        self._estimate = jax.jit(_est)

    # -- update paths (all funnel through launch_insert / _apply_incr so
    # the fault point and the top-k hook see every batch) ----------------

    def launch_insert(self, staged):
        d_keys, d_lengths, B = staged
        faults.fire("cms.update", filter=self.config.key_name, batch=B)
        with obs.phase("kernel"):
            self.words = self._insert(self.words, d_keys, d_lengths)
        self.n_inserted += B
        self._post_update(d_keys, d_lengths, B)
        return self.words

    def insert_batch(self, keys: Sequence[bytes | str]) -> None:
        out = self.launch_insert(self.stage_batch(keys))
        if obs.current() is not None:
            with obs.phase("kernel"):
                self._kernel_fence(out)

    def insert_arrays(self, keys_u8, lengths, *, n_valid=None) -> None:
        faults.fire("cms.update", filter=self.config.key_name)
        self.words = self._insert(self.words, keys_u8, lengths)
        B = int(keys_u8.shape[0]) if n_valid is None else n_valid
        self.n_inserted += B
        self._post_update(keys_u8, lengths, B)

    def increment_batch(
        self, keys: Sequence[bytes | str], increments: Sequence[int]
    ) -> np.ndarray:
        """Weighted increment (``CMSIncrBy``); returns the POST-update
        estimates (uint32[B]) — the verb's Redis-parity response."""
        if len(increments) != len(keys):
            raise ValueError(
                f"{len(increments)} increments for {len(keys)} keys"
            )
        incs = [int(i) for i in increments]
        if any(i < 0 or i >= (1 << 32) for i in incs):
            raise ValueError("increments must be u32 (>= 0)")
        keys_u8, lengths, B = self._pack_padded(keys)
        padded = np.zeros(lengths.shape, np.uint32)
        padded[:B] = np.asarray(incs, np.uint32)
        d_keys, d_lengths = self._stage_batch(keys_u8, lengths)
        faults.fire("cms.update", filter=self.config.key_name, batch=B)
        with obs.phase("kernel"):
            self.words = self._incr(
                self.words, d_keys, d_lengths, jnp.asarray(padded)
            )
            if obs.current() is not None:
                self._kernel_fence(self.words)
        self.n_inserted += B
        self._post_update(d_keys, d_lengths, B)
        with obs.phase("kernel_query"):
            est = self._estimate(self.words, d_keys, d_lengths)
        with obs.phase("d2h"):
            out = np.asarray(est)
        return out[:B]

    def _post_update(self, d_keys, d_lengths, B: int) -> None:
        """Per-batch post-update hook; TopKSketch refreshes its heap."""
        obs_counters.incr("cms_keys_incremented", B)

    # -- reads -----------------------------------------------------------

    def estimate_batch(self, keys: Sequence[bytes | str]) -> np.ndarray:
        """Point estimates (``CMSQuery``): uint32[B], only ever >= truth."""
        keys_u8, lengths, B = self._pack_padded(keys)
        d_keys, d_lengths = self._stage_batch(keys_u8, lengths)
        with obs.phase("kernel_query"):
            est = self._estimate(self.words, d_keys, d_lengths)
            if obs.current() is not None:
                self._kernel_fence(est)
        with obs.phase("d2h"):
            out = np.asarray(est)
        self.n_queried += B
        return out[:B]

    # -- stats -----------------------------------------------------------

    def fill_ratio(self) -> float:
        """Fraction of NONZERO counters (collision-pressure signal; the
        bloom fill/FPR model doesn't apply to counter grids)."""
        nz = int(np.asarray((self.words != 0).sum()))
        return nz / (self.width * self.depth)

    def stats(self) -> dict:
        return {
            "kind": self.config.kind,
            "width": self.width,
            "depth": self.depth,
            "n_inserted": self.n_inserted,
            "n_queried": self.n_queried,
            "fill_ratio": self.fill_ratio(),
        }


class TopKSketch(CountMinSketch):
    """CMS + host-side heavy-hitter heap of the ``config.topk`` largest
    estimates seen. Updated synchronously after each batch from a
    device-side estimate pass, so TopKList is a pure host read."""

    KINDS = ("topk",)

    def __init__(self, config: FilterConfig):
        super().__init__(config)
        self._heap: dict[bytes, int] = {}

    def _post_update(self, d_keys, d_lengths, B: int) -> None:
        super()._post_update(d_keys, d_lengths, B)
        if not B:
            return
        with obs.phase("kernel_query"):
            est = self._estimate(self.words, d_keys, d_lengths)
        with obs.phase("d2h"):
            est_np = np.asarray(est)
            rows = np.asarray(d_keys)
            lens = np.asarray(d_lengths)
        heap, cap = self._heap, self.config.topk
        for i in range(B):
            key = rows[i, : lens[i]].tobytes()
            count = int(est_np[i])
            if key in heap:
                heap[key] = max(heap[key], count)
            elif len(heap) < cap:
                heap[key] = count
            else:
                smallest = min(heap, key=heap.get)
                if count > heap[smallest]:
                    del heap[smallest]
                    heap[key] = count
        obs_counters.incr("topk_heap_updates", B)

    def topk_list(self) -> list:
        """[(key bytes, estimate)] sorted by estimate desc, then key —
        deterministic so replicas/goldens agree."""
        return sorted(self._heap.items(), key=lambda kv: (-kv[1], kv[0]))

    def clear(self) -> None:
        super().clear()
        self._heap = {}

    # -- checkpoint extra block ------------------------------------------

    def sketch_extra(self) -> dict:
        return {
            "topk_heap": [[k.hex(), c] for k, c in self.topk_list()]
        }

    def load_sketch_extra(self, extra: dict) -> None:
        heap = (extra or {}).get("topk_heap") or []
        self._heap = {bytes.fromhex(k): int(c) for k, c in heap}
