"""Sketch plane (ISSUE 19): non-bloom filter kinds as pluggable peers.

``tpubloom.sketch`` hosts the filter kinds whose storage is NOT a bloom
bit array — the cuckoo filter (true deletion without counters) and the
count-min sketch / top-k heavy-hitter pair (frequency workloads). Each
kind plugs into the serving stack through :mod:`tpubloom.sketch.registry`
(factory + checkpoint blob tag + per-kind replay-safety classification),
so replication, sync-quorum barriers, HA promotion, cluster migration,
tenant paging, streaming ingest, and tracing are inherited from the
shared planes — never re-implemented per kind. See the README "Filter
kinds" section for the add-a-kind recipe and the lint checks that
enforce each step.
"""

from tpubloom.sketch.registry import (  # noqa: F401
    KindSpec,
    blob_format,
    build,
    is_sketch,
    kind_of,
    replay_unsafe_insert,
    sketch_kinds,
    spec,
    supports_delete,
)
