"""Version constant.

Parity: the reference gem exposes ``Redis::Bloomfilter::VERSION``
(SURVEY.md §2.1, expected at lib/redis-bloomfilter/version.rb [PK]).
"""

__version__ = "0.1.0"
