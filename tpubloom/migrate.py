"""Flat -> blocked checkpoint migration (the flat-layout decision).

The flat layout is this framework's *compatibility* spec: its positions
are the reference's SETBIT/GETBIT Redis-bitmap positions (BASELINE
north_star hot path; ``tpubloom.utils.packing``), so a flat checkpoint is
readable by the reference's ``:ruby`` driver and vice versa. It is NOT
the throughput layout: k scattered positions per key across a 512 MiB
array is exactly the random-access pattern TPU HBM cannot stream
(measured 2.2M keys/s on v5e vs 50M+ for blocked — benchmarks/RESULTS).

Teams that outgrow the compat layout migrate to blocked. A bloom filter
cannot enumerate its members, so migration REQUIRES the caller's key
stream (the system of record that originally fed the filter); the tool

* streams keys in bounded batches (constant memory at any corpus size),
* verifies every batch against the flat filter as it goes — a key the
  flat filter does not contain means the stream is not the filter's
  source and the migration would silently produce a filter with
  different answers; we fail fast instead (``strict=False`` downgrades
  to counting the misses, for streams known to be a superset),
* inserts into a fresh blocked filter and writes its checkpoint.

CLI: ``python -m tpubloom.migrate --src DIR --key-name NAME --keys FILE``
(newline-delimited keys; '-' = stdin). See ``migrate_checkpoint``.
"""

from __future__ import annotations

import itertools
from typing import Iterable, Optional

import numpy as np

from tpubloom import checkpoint as ckpt
from tpubloom.config import FilterConfig
from tpubloom.filter import BlockedBloomFilter


DEFAULT_BATCH = 65536


def migrate_checkpoint(
    src_sink,
    keys: Iterable[bytes | str],
    *,
    dst_sink=None,
    src_config: FilterConfig,
    block_bits: int = 512,
    dst_key_name: Optional[str] = None,
    batch_size: int = DEFAULT_BATCH,
    strict: bool = True,
) -> dict:
    """Rebuild a flat filter's contents as a blocked filter, from the
    caller's key stream, and checkpoint the result.

    Args:
      src_sink: checkpoint sink holding the flat filter (newest seq used).
      keys: the key stream to re-insert — the filter's system of record.
      dst_sink: sink for the blocked checkpoint (defaults to ``src_sink``
        under ``dst_key_name``).
      src_config: the flat filter's config (identity-checked on restore).
      block_bits: blocked geometry for the destination (same m, k, seed).
      dst_key_name: destination namespace (default ``<key_name>.blocked``).
      batch_size: keys per device batch (bounded memory).
      strict: raise if a streamed key is absent from the flat filter
        (stream/filter mismatch); ``False`` records ``missing`` instead.

    Returns a summary dict: ``{"migrated", "missing", "seq", "dst_config"}``.
    """
    if src_config.block_bits or src_config.counting or src_config.shards > 1:
        raise ValueError("migration source must be a flat single-device config")
    src = ckpt.restore(src_config, src_sink, expect_scalable=False)
    if src is None:
        raise ValueError(
            f"no checkpoint for {src_config.key_name!r} in the source sink"
        )
    dst_config = src_config.replace(
        block_bits=block_bits,
        block_hash="auto",
        key_name=dst_key_name or f"{src_config.key_name}.blocked",
    )
    dst = BlockedBloomFilter(dst_config)
    migrated = 0
    missing = 0
    it = iter(keys)
    while True:
        chunk = list(itertools.islice(it, batch_size))
        if not chunk:
            break
        present = src.include_batch(chunk)
        if not present.all():
            absent = int((~present).sum())
            if strict:
                i = int(np.argmin(present))
                raise ValueError(
                    f"key stream is not this filter's source: {absent} of "
                    f"{len(chunk)} keys in batch are absent from the flat "
                    f"filter (first: {chunk[i]!r}); pass strict=False only "
                    f"if the stream is a known superset"
                )
            missing += absent
            chunk = [kk for kk, p in zip(chunk, present) if p]
        if chunk:
            dst.insert_batch(chunk)
            migrated += len(chunk)
    sink = dst_sink if dst_sink is not None else src_sink
    seq = ckpt.save(dst, sink, extra={"migrated_from": src_config.key_name})
    return {
        "migrated": migrated,
        "missing": missing,
        "seq": seq,
        "dst_config": dst_config.to_dict(),
    }


def _main(argv=None) -> int:
    import argparse
    import json
    import os
    import sys

    # honor JAX_PLATFORMS=cpu BEFORE any backend initializes: this image's
    # axon sitecustomize force-sets jax_platforms via jax.config.update,
    # overriding the env var (same dance as __graft_entry__)
    if "cpu" in os.environ.get("JAX_PLATFORMS", "").split(","):
        import jax

        jax.config.update("jax_platforms", "cpu")

    ap = argparse.ArgumentParser(
        description="Migrate a flat (Redis-bitmap-compatible) tpubloom "
        "checkpoint to the blocked throughput layout by re-driving the "
        "key stream."
    )
    ap.add_argument("--src", required=True, help="source checkpoint directory")
    ap.add_argument("--dst", help="destination directory (default: --src)")
    ap.add_argument("--key-name", required=True)
    ap.add_argument("--dst-key-name")
    ap.add_argument("--m", type=int, required=True, help="flat filter m (bits)")
    ap.add_argument("--k", type=int, required=True)
    ap.add_argument("--seed", type=int, default=None)
    ap.add_argument("--key-len", type=int, default=16)
    ap.add_argument("--block-bits", type=int, default=512)
    ap.add_argument("--batch-size", type=int, default=DEFAULT_BATCH)
    ap.add_argument(
        "--keys", required=True,
        help="newline-delimited key file ('-' = stdin); keys are used as "
        "raw bytes without the trailing newline",
    )
    ap.add_argument(
        "--lenient", action="store_true",
        help="skip (and count) keys absent from the flat filter instead of "
        "failing — only for streams known to be a superset",
    )
    args = ap.parse_args(argv)

    kw = {} if args.seed is None else {"seed": args.seed}
    src_config = FilterConfig(
        m=args.m, k=args.k, key_len=args.key_len, key_name=args.key_name, **kw
    )
    fh = sys.stdin.buffer if args.keys == "-" else open(args.keys, "rb")
    try:
        key_iter = (line.rstrip(b"\n") for line in fh)
        summary = migrate_checkpoint(
            ckpt.FileSink(args.src),
            key_iter,
            dst_sink=ckpt.FileSink(args.dst) if args.dst else None,
            src_config=src_config,
            block_bits=args.block_bits,
            dst_key_name=args.dst_key_name,
            batch_size=args.batch_size,
            strict=not args.lenient,
        )
    finally:
        if fh is not sys.stdin.buffer:
            fh.close()
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())
