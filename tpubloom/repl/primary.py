"""Primary side of the replication protocol (PSYNC parity).

A replica opens the server-streaming ``ReplStream`` RPC with a cursor
(the last op seq it fully applied; absent on first contact). The
primary answers the way Redis PSYNC does:

* **full resync** — cursor absent, or the checkpoint-keyed log
  truncation has already dropped the records past it: the primary
  snapshots every live filter (checkpoint-format blobs, each stamped
  with the op seq its bytes cover) and streams them, then tails the log
  from the oldest snapshot seq. The per-filter ``applied_seq`` stamps
  make the handoff race-free: a record the snapshot already contains is
  skipped by the replica's seq gate, not re-applied.
* **partial resync** — cursor still inside the log: ack and stream the
  tail (the Redis repl-backlog case).

Either way the stream then follows the live log (:meth:`OpLog.wait_for`)
and idles with heartbeats carrying the head seq, which is what the
replica's ``repl_lag_seq`` gauge measures against.

The :class:`ReplicaSessions` hub tracks connected streams (gauge
``repl_connected_replicas``; per-session cursors feed
``repl_max_replica_lag_seq`` and bound log truncation so a merely-slow
replica is not forced into a full resync).

Synchronous replication (ISSUE 5): the sync frames carry the session id
(``sid``), and the replica opens a companion client-streaming
``ReplAck`` RPC echoing it with every applied cursor
(:func:`repl_ack`). :meth:`ReplicaSessions.ack` folds the frames into
per-replica **acked** cursors, and :meth:`ReplicaSessions.wait_acked`
is the blocking primitive behind both the ``Wait`` RPC (Redis ``WAIT``
parity) and the ``min-replicas-to-write`` commit barrier — waiters
count replicas whose acked seq is at or past a record's seq, with the
currently-blocked count exported as the ``wait_blocked_current`` gauge
and per-replica acked seqs as ``repl_acked_seq{replica}``.

Fault point ``repl.stream_send`` fires before every snapshot/record
send — the chaos suite kills a stream mid-batch with it and proves the
reconnect replays nothing twice. ``repl.ack_recv`` fires per received
ack frame (a firing kills the ack stream; the replica re-opens it).
"""

from __future__ import annotations

import itertools
import threading
import time
import zlib

import msgpack

from tpubloom import faults
from tpubloom.obs import counters as _counters
from tpubloom.utils import locks as _locks

#: How often an idle stream emits a heartbeat (seconds).
DEFAULT_HEARTBEAT_S = 0.5

#: Max records per poll round before re-checking liveness/cancellation.
STREAM_BATCH = 256

#: Capability flag a replica sends to opt into coalesced+compressed
#: record frames (ISSUE 4 satellite — WAN links). Negotiated: the
#: primary only batches when the replica advertised it AND the server
#: was started with ``--repl-batch-bytes``.
CAP_BATCH_ZLIB = "batch-zlib"


class ReplicaSessions:
    """Connected-replica registry: addresses, cursors, acked seqs, lag
    gauges, and the wait-for-quorum primitive (ISSUE 5)."""

    def __init__(self):
        self._cond = _locks.named_condition("repl.sessions")
        self._ids = itertools.count()
        self._sessions: dict[int, dict] = {}
        self._waiters = 0

    def register(self, peer: str, listen: str | None = None) -> int:
        """``listen`` is the replica's ANNOUNCED serving address (its
        gRPC listener, not the ephemeral peer port) — what sentinels
        discover replicas by, Redis ``replica-announce-ip/port`` parity."""
        with self._cond:
            sid = next(self._ids)
            self._sessions[sid] = {
                "sid": sid,
                "peer": peer,
                "listen": listen,
                "cursor": 0,
                #: newest op seq the replica has ACKNOWLEDGED as applied
                #: (via ReplAck) — what Wait/min-replicas block on; the
                #: stream-side cursor only says what was SENT to it
                "acked": 0,
                #: monotonic time of the last ack FRAME (idle re-acks
                #: refresh it) — the commit barrier's freshness gate
                #: (ISSUE 6): an old-enough acked_at means the replica
                #: stopped talking, and its acked cursor is history, not
                #: durability
                "acked_at": 0.0,
                "connected_at": time.time(),
            }
            n = len(self._sessions)
        _counters.set_gauge("repl_connected_replicas", n)
        return sid

    def update(self, sid: int, cursor: int, head: int) -> None:
        with self._cond:
            sess = self._sessions.get(sid)
            if sess is not None:
                sess["cursor"] = cursor
            lags = [head - s["cursor"] for s in self._sessions.values()]
        _counters.set_gauge(
            "repl_max_replica_lag_seq", max(lags) if lags else 0
        )

    def ack(self, sid: int, seq: int) -> None:
        """Fold one ReplAck frame in: the replica behind session ``sid``
        has fully applied every record up to ``seq``. Monotone per
        session (a late/reordered frame never rewinds), and every
        advance wakes the quorum waiters."""
        with self._cond:
            sess = self._sessions.get(sid)
            if sess is None:
                return  # stream already reconnected under a new sid
            sess["acked_at"] = time.monotonic()
            if seq > sess["acked"]:
                sess["acked"] = seq
                self._cond.notify_all()
            elif self._waiters:
                # the seq did not advance but the FRESHNESS did (an idle
                # re-ack): an age-gated quorum waiter may be satisfiable
                # by exactly this refresh
                self._cond.notify_all()

    def count(self) -> int:
        with self._cond:
            return len(self._sessions)

    def _acked_locked(self, seq: int, max_age) -> int:
        """Count under the condition: acked cursor at/past ``seq``, and —
        with ``max_age`` (seconds) — an ack frame within that window.
        Redis ``min-replicas-max-lag`` parity: lag is time since the
        last REPLCONF ACK, so a replica that acked the seq long ago and
        then went silent does not count toward a freshness-gated quorum."""
        now = time.monotonic() if max_age is not None else 0.0
        return sum(
            1
            for s in self._sessions.values()
            if s["acked"] >= seq
            and (max_age is None or now - s["acked_at"] <= max_age)
        )

    def count_acked(self, seq: int, *, max_age=None) -> int:
        """Replicas whose acked cursor is at or past ``seq`` (optionally
        only those whose last ack frame is ``max_age``-fresh; ``<= 0``
        disables the gate, Redis ``min-replicas-max-lag 0`` parity)."""
        if max_age is not None and max_age <= 0:
            max_age = None
        with self._cond:
            return self._acked_locked(seq, max_age)

    def wait_acked(
        self,
        seq: int,
        needed: int,
        timeout: float,
        *,
        require_connected: int = 0,
        max_age=None,
    ) -> int:
        """Block until at least ``needed`` replicas have acked ``seq``
        (or ``timeout`` elapses); returns the count actually acked —
        Redis WAIT semantics, the caller decides whether falling short
        is an error. ``needed <= 0`` returns the current count
        immediately. Blocked waiters are the ``wait_blocked_current``
        gauge.

        ``require_connected`` is the commit barrier's mid-wait
        attainability check: once fewer than that many replicas are even
        CONNECTED the quorum cannot complete this round, so return the
        current count immediately instead of sleeping out the timeout
        (``unregister`` wakes waiters exactly for this). The Wait RPC
        passes 0 — a replica may reconnect within its window, and Redis
        WAIT rides out the full timeout.

        ``max_age`` (seconds, ISSUE 6) additionally requires each counted
        replica's last ack FRAME to be that fresh — the commit barrier
        passes its lag budget here so a replica that acked once and went
        silent cannot keep satisfying durability quorums forever.
        ``max_age <= 0`` means NO freshness gate (Redis
        ``min-replicas-max-lag 0`` semantics: the check is disabled, not
        infinitely strict — and a 0 gate would also busy-spin the
        wait loop below)."""
        _locks.note_blocking("repl.wait_acked")
        if max_age is not None and max_age <= 0:
            max_age = None
        deadline = time.monotonic() + max(0.0, timeout)
        with self._cond:
            count = self._acked_locked(seq, max_age)
            if needed <= 0 or count >= needed:
                return count
            self._waiters += 1
            _counters.set_gauge("wait_blocked_current", self._waiters)
            try:
                while True:
                    count = self._acked_locked(seq, max_age)
                    remaining = deadline - time.monotonic()
                    if (
                        count >= needed
                        or remaining <= 0
                        or len(self._sessions) < require_connected
                    ):
                        return count
                    # with an age gate, a quorum member can go STALE
                    # mid-wait without any notify — cap the sleep so the
                    # loop re-evaluates freshness on its own clock
                    if max_age is not None:
                        remaining = min(remaining, max_age / 2.0)
                    self._cond.wait(remaining)
            finally:
                self._waiters -= 1
                _counters.set_gauge("wait_blocked_current", self._waiters)

    def unregister(self, sid: int) -> None:
        with self._cond:
            self._sessions.pop(sid, None)
            n = len(self._sessions)
            # a vanished replica can no longer ack: re-evaluate quorums
            # now rather than at their timeout
            self._cond.notify_all()
        _counters.set_gauge("repl_connected_replicas", n)
        if not n:
            _counters.set_gauge("repl_max_replica_lag_seq", 0)

    def min_cursor(self) -> int | None:
        """Slowest connected replica's cursor (None when no replicas) —
        log truncation stays behind it so live streams never lose their
        tail mid-flight."""
        with self._cond:
            if not self._sessions:
                return None
            return min(s["cursor"] for s in self._sessions.values())

    def describe(self) -> list:
        with self._cond:
            return [dict(s) for s in self._sessions.values()]


def _batched_frames(records: list, batch_bytes: int):
    """Coalesce records into ``{"kind": "records", "z": <zlib level-1 of
    a msgpack record list>, ...}`` frames of roughly ``batch_bytes`` of
    raw payload each (one oversized record still ships alone). Level 1:
    op records are msgpack maps full of repeated keys and key bytes —
    cheap compression wins most of what's winnable, and the stream stays
    CPU-light."""
    group: list = []
    group_bytes = 0
    for r in records:
        size = len(msgpack.packb(r, use_bin_type=True))
        if group and group_bytes + size > batch_bytes:
            yield _pack_group(group)
            group, group_bytes = [], 0
        group.append(r)
        group_bytes += size
    if group:
        yield _pack_group(group)


def _pack_group(group: list) -> dict:
    raw = msgpack.packb(group, use_bin_type=True)
    z = zlib.compress(raw, 1)
    _counters.incr("repl_stream_batched_frames")
    _counters.incr("repl_stream_batched_bytes_raw", len(raw))
    _counters.incr("repl_stream_batched_bytes_wire", len(z))
    return {
        "kind": "records",
        "z": z,
        "count": len(group),
        "first_seq": group[0]["seq"],
        "last_seq": group[-1]["seq"],
    }


def repl_stream(service, req: dict, context, *, heartbeat_s: float = DEFAULT_HEARTBEAT_S):
    """Generator behind the ``ReplStream`` RPC (dicts; the server layer
    msgpack-encodes each one)."""
    oplog = service.oplog
    if oplog is None:
        yield {
            "kind": "error",
            "code": "UNSUPPORTED",
            "message": "this server has no op log (start it with "
            "--repl-log-dir to serve replicas)",
        }
        return
    sessions: ReplicaSessions = service.repl_sessions
    cursor = req.get("cursor")
    caps = set(req.get("caps") or ())
    batch_bytes = getattr(service, "repl_batch_bytes", None)
    use_batch = bool(batch_bytes) and CAP_BATCH_ZLIB in caps
    sid = sessions.register(
        getattr(context, "peer", lambda: "?")(), listen=req.get("listen")
    )
    try:
        # a cursor is only resumable against the SAME log identity
        # (Redis replid parity): a rewound/recreated log reuses seq
        # numbers, so a stale-id cursor would silently swallow records.
        # Post-failover, the promoted node's ALIAS (replid2 parity)
        # extends "same identity" to the old primary's id up to the
        # promotion point — survivors partial-resync instead of paying
        # a full resync.
        if cursor is None or not oplog.resumable(cursor, req.get("log_id")):
            _counters.incr("repl_full_resyncs")
            names, snaps, plan_seq = service.snapshot_plan()
            yield {
                "kind": "full_sync_begin",
                "filters": names,
                "seq": oplog.last_seq,
                "log_id": oplog.log_id,
            }
            seqs = [plan_seq]
            for name, blob, applied_seq in snaps:
                faults.fire("repl.stream_send")
                yield {
                    "kind": "snapshot",
                    "name": name,
                    "blob": blob,
                    "applied_seq": applied_seq,
                }
                seqs.append(applied_seq)
            # tail from the oldest snapshot point, clamped to the log
            # head AT PLAN TIME: a create committed after the plan froze
            # is not in `names`, so its record must be streamed — while
            # records a snapshot already contains are skipped by the
            # replica's per-filter gate
            cursor = min(seqs)
            yield {
                "kind": "full_sync_end",
                "cursor": cursor,
                "log_id": oplog.log_id,
                "epoch": getattr(service, "epoch", 0),
                # the replica echoes the session id on its ReplAck
                # frames — how acks land on THIS session's acked cursor
                "sid": sid,
            }
        else:
            _counters.incr("repl_partial_resyncs")
            yield {
                "kind": "partial_sync",
                "cursor": cursor,
                "log_id": oplog.log_id,
                "epoch": getattr(service, "epoch", 0),
                "sid": sid,
            }
        sessions.update(sid, cursor, oplog.last_seq)
        follower = oplog.follower(cursor)
        stream_log_id = oplog.log_id
        while context.is_active() and not service.draining:
            if oplog.log_id != stream_log_id:
                # the log identity rotated UNDER this stream (a chained
                # upstream full-resynced and reset its log): the
                # subscriber's cursor belongs to the old identity — end
                # the stream so its reconnect re-handshakes (and pays
                # the full resync the reset implies)
                _counters.incr("repl_stream_cut_identity_rotated")
                return
            batch = follower.next_batch(STREAM_BATCH)
            if use_batch and len(batch) > 1:
                for frame in _batched_frames(batch, batch_bytes):
                    faults.fire("repl.stream_send")
                    yield frame
                _counters.incr("repl_records_streamed", len(batch))
            else:
                for rec in batch:
                    faults.fire("repl.stream_send")
                    yield {"kind": "record", **rec}
                    _counters.incr("repl_records_streamed")
            cursor = follower.cursor
            sessions.update(sid, cursor, oplog.last_seq)
            if not batch and not oplog.wait_for(
                cursor + 1, timeout=heartbeat_s
            ):
                yield {
                    "kind": "heartbeat",
                    "seq": oplog.last_seq,
                    "ts": time.time(),
                    "epoch": getattr(service, "epoch", 0),
                }
    finally:
        sessions.unregister(sid)


def repl_ack(service, request_iterator, context):
    """Behavior behind the client-streaming ``ReplAck`` RPC (ISSUE 5):
    consume ``{"sid", "seq"}`` frames from one replica for the lifetime
    of its ack stream, folding each into the matching session's acked
    cursor. Returns the single response dict once the stream ends.

    Fault point ``repl.ack_recv`` fires per frame; a firing propagates
    out of the handler — gRPC fails the RPC, the replica notices the
    dead ack stream at its next heartbeat and re-opens it (re-sending
    its current cursor, so no ack is permanently lost)."""
    from tpubloom.server import protocol

    frames = 0
    for raw in request_iterator:
        faults.fire("repl.ack_recv")
        try:
            frame = protocol.decode(raw)
        except Exception:
            _counters.incr("repl_ack_decode_errors")
            continue
        sid, seq = frame.get("sid"), frame.get("seq")
        if sid is None or seq is None:
            continue
        frames += 1
        # counted per FRAME (idle re-acks included) so the pair
        # sent-vs-received stays comparable: a growing gap means real
        # ack loss, not the monotone-advance filter in ack()
        _counters.incr("repl_acks_received")
        service.repl_sessions.ack(int(sid), int(seq))
    return {"ok": True, "frames": frames}
