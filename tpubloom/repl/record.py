"""Op-log record framing (ISSUE 3).

One record = one mutating RPC, exactly as it committed on the primary:

``MAGIC(4) | body_len u32le | body_crc32c u32le | body``

where ``body`` is the msgpack map ``{"seq", "method", "rid", "req",
"ts"}``. ``seq`` is the log-global monotonic sequence number (the
replication cursor — PSYNC-offset parity), ``rid`` the client request id
that committed the op (kept so a replayed op correlates with the
original slowlog/trace entries), ``req`` the decoded request map minus
transport-only fields, ``ts`` the primary's commit wall time (drives
``repl_lag_seconds``).

Integrity reuses :func:`tpubloom.utils.crc32c.crc32c` — the same
polynomial the checkpoint v2 framing declares, so one checksum
implementation covers both durability formats. A record whose CRC or
length does not check out is *torn*: :func:`scan_buffer` stops there and
reports the longest valid prefix, which is what log recovery truncates
to (Redis ``aof-load-truncated`` parity).
"""

from __future__ import annotations

from typing import Optional

import msgpack

from tpubloom.utils.crc32c import crc32c

#: 4-byte per-record magic: cheap resync sentinel + format versioning.
MAGIC = b"TPR1"
HEADER_LEN = len(MAGIC) + 4 + 4


def encode_record(rec: dict) -> bytes:
    """Frame one record dict (caller provides seq/method/rid/req/ts)."""
    body = msgpack.packb(rec, use_bin_type=True)
    return (
        MAGIC
        + len(body).to_bytes(4, "little")
        + crc32c(body).to_bytes(4, "little")
        + body
    )


def decode_record(buf: bytes, offset: int = 0) -> Optional[tuple]:
    """Decode the record at ``offset``; ``(record, next_offset)`` or None
    if the bytes from ``offset`` on do not form one intact record (short
    header, short body, bad magic, CRC mismatch — all read as *torn*)."""
    end = offset + HEADER_LEN
    if len(buf) < end:
        return None
    if buf[offset : offset + 4] != MAGIC:
        return None
    body_len = int.from_bytes(buf[offset + 4 : offset + 8], "little")
    body_crc = int.from_bytes(buf[offset + 8 : end], "little")
    body = buf[end : end + body_len]
    if len(body) != body_len or crc32c(body) != body_crc:
        return None
    return msgpack.unpackb(body, raw=False), end + body_len


def scan_buffer(buf: bytes, offset: int = 0):
    """Parse records until the buffer ends or turns invalid.

    Returns ``(records, valid_len, clean)`` — ``valid_len`` is the byte
    offset just past the last intact record (the truncation point for
    torn-tail repair), ``clean`` is True iff the buffer ended exactly on
    a record boundary."""
    records = []
    while True:
        parsed = decode_record(buf, offset)
        if parsed is None:
            return records, offset, offset == len(buf)
        rec, offset = parsed
        records.append(rec)
