"""Replica side: consume a primary's ``ReplStream`` and apply it.

``python -m tpubloom.server --replica-of host:port`` runs the normal
server read-only (writes get ``READONLY``, Redis parity) with one
:class:`ReplicaApplier` thread behind it:

* **sync** — first contact sends no cursor → full resync (snapshot blobs
  install via :meth:`BloomService.install_snapshot`, then the log tail);
  reconnects send the last fully-applied seq → partial resync when the
  primary still has the tail, a fresh full resync otherwise.
* **idempotent apply** — every record is gated twice: the stream-global
  cursor (records at or below it are never re-requested) and the
  per-filter ``applied_seq`` (a record already contained in an installed
  snapshot is skipped, counted in ``repl_records_skipped``). Killing the
  stream mid-batch and reconnecting therefore re-applies nothing — the
  chaos suite pins this with the ``repl.stream_send``/``repl.apply``
  fault points.
* **lag** — ``repl_lag_seq`` (head seq from records/heartbeats minus the
  applied cursor) and ``repl_lag_seconds`` (apply-time minus the
  record's primary commit time; 0 when caught up on a heartbeat).
* **liveness** — transport errors back off exponentially
  (``repl_reconnects``); the link state lands in Health via
  :meth:`status` (``link: connected/connecting/lost``).
* **acks** (ISSUE 5) — alongside the sync stream the applier keeps a
  client-streaming ``ReplAck`` RPC open (:class:`_AckSender`), echoing
  the session id from the sync frame with every applied cursor
  (coalesced latest-wins + periodic re-ack). This is the upstream half
  of the primary's ``WAIT`` / ``min-replicas-to-write`` durability
  gate; fault point ``repl.ack`` drops individual frames (ack loss).
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
import zlib
from typing import Optional

import grpc
import msgpack

from tpubloom import faults
from tpubloom.obs import blackbox as obs_blackbox
from tpubloom.obs import counters as _counters
from tpubloom.obs import trace as obs_trace
from tpubloom.server import protocol
from tpubloom.utils import crcjson
from tpubloom.utils import locks

log = logging.getLogger("tpubloom.repl")


class FullResyncNeeded(Exception):
    """Raised by the apply path when a record's effect cannot be derived
    from the stream alone — e.g. a ``CreateFilter`` that bootstrapped
    state from a checkpoint the replica does not have, or a chained
    replica's local log refusing a gapped re-append. The applier drops
    its cursor and reconnects: the full-resync snapshot carries the
    state the record could not."""

    def __init__(self, name: str, reason: Optional[str] = None):
        super().__init__(
            reason
            or f"record for filter {name!r} references state only a full "
            f"resync can transfer"
        )
        self.name = name


class ReplicaStateStore:
    """Replica-side persistence of the replication cursor (ISSUE 4
    satellite): ``<dir>/repl_cursor.json`` holds the last fully-applied
    seq + the primary log identity it belongs to, CRC32C-checked so a
    torn write reads as "no cursor" (→ full resync — the safe
    direction) rather than a bogus resume point. With it, a replica
    restart bootstraps from its local checkpoints and PARTIAL-resyncs
    instead of always paying a full one."""

    CURSOR_FILE = "repl_cursor.json"

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, self.CURSOR_FILE)

    def load(self) -> Optional[dict]:
        """``{"cursor": int, "log_id": str}`` or None (absent/corrupt)."""
        data = crcjson.load(self.path, ("cursor", "log_id"))
        if data is None:
            return None
        try:
            return {"cursor": int(data["cursor"]), "log_id": data["log_id"]}
        except (ValueError, TypeError):
            return None

    def store(self, cursor: int, log_id: Optional[str]) -> None:
        if log_id is None:
            return
        crcjson.store(self.path, {"cursor": int(cursor), "log_id": log_id})

    def clear(self) -> None:
        try:
            os.unlink(self.path)
        except OSError:
            pass


def bootstrap_from_local(service, state_store: Optional[ReplicaStateStore]):
    """Restart path of a replica with local durability: rebuild state
    from the creation manifest + local checkpoints (chained replicas:
    the caller already ran ``replay_oplog``) and return the
    ``(cursor, log_id)`` to resume the stream from — or ``(None, None)``
    when only a full resync is safe.

    The resume cursor is the MIN over restored filters of the op seq
    their restored bytes cover: every record at or below it is contained
    in some filter's restored state (per-filter ``repl_seq`` gates skip
    the overlap above it), so nothing is lost and nothing double-applies.
    """
    saved = state_store.load() if state_store is not None else None
    if saved is None or not saved.get("log_id"):
        return None, None
    if service.oplog is not None:
        # chained replica: replay already drove the local log over the
        # restored checkpoints — state coverage IS the log head
        return service.oplog.last_seq, saved["log_id"]
    manifest = service._manifest_read() or {}
    if not manifest:
        # empty filter set at the persisted cursor is exactly the state
        return saved["cursor"], saved["log_id"]
    seqs = []
    for name, create_req in manifest.items():
        try:
            service.CreateFilter(
                {**create_req, "exist_ok": True, "restore": True}
            )
        except Exception:
            log.exception(
                "replica bootstrap: re-creating filter %r failed — "
                "falling back to a full resync", name,
            )
            return None, None
        mf = service._filters.get(name)
        if mf is None or mf.applied_seq <= 0:
            # no restorable checkpoint for this filter: its state cannot
            # be rebuilt locally, only a full resync carries it
            return None, None
        seqs.append(mf.applied_seq)
    cursor = min(seqs)
    _counters.incr("repl_bootstrap_partial_resyncs")
    log.info(
        "replica bootstrap: %d filter(s) restored locally; resuming the "
        "stream from seq %d", len(seqs), cursor,
    )
    return cursor, saved["log_id"]


class _AckSender:
    """Replica→primary acknowledgement stream (ISSUE 5): feeds the
    client-streaming ``ReplAck`` RPC with ``{"sid", "seq"}`` frames.

    Coalescing is latest-wins: the applier calls :meth:`update` per
    applied record, the generator ships whatever the newest cursor is
    when gRPC drains it — a fast apply loop costs one frame per drain,
    not one per record. An idle stream re-sends the current cursor
    every ``reack_s`` seconds, which (a) keeps the primary's ack
    freshness view live and (b) heals any frame lost in flight (the
    ``repl.ack`` fault point drops frames exactly there, so a chaos run
    recovers the moment it disarms).
    """

    def __init__(self, channel, sid: int, *, reack_s: float = 0.5):
        self.sid = sid
        self.reack_s = reack_s
        self._cond = locks.named_condition("repl.ack_sender")
        self._seq: Optional[int] = None
        self._sent: Optional[int] = None
        self._closed = False
        multi = channel.stream_unary(
            protocol.method_path("ReplAck"),
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )
        self._future = multi.future(self._frames(), timeout=None)

    @property
    def broken(self) -> bool:
        """True once the RPC ended (server killed the ack stream, e.g.
        an injected ``repl.ack_recv``) — the applier re-opens it."""
        return self._future.done() and not self._closed

    def update(self, seq: int) -> None:
        with self._cond:
            if self._seq is None or seq > self._seq:
                self._seq = seq
                self._cond.notify_all()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._future.cancel()

    def _frames(self):
        while True:
            with self._cond:
                if self._closed:
                    return
                if self._seq is None or self._seq == self._sent:
                    self._cond.wait(self.reack_s)
                if self._closed:
                    return
                seq = self._seq
                if seq is None:
                    continue
                self._sent = seq
            try:
                # ack-loss injection: a firing drops THIS frame only —
                # the seq stays marked sent, and the periodic re-ack
                # path retries it after reack_s (heals once disarmed)
                faults.fire("repl.ack")
            except faults.InjectedFault:
                _counters.incr("repl_acks_dropped")
                continue
            _counters.incr("repl_acks_sent")
            yield protocol.encode({"sid": self.sid, "seq": seq})


class ReplicaApplier:
    """Background thread that keeps a local (read-only) service in sync
    with a primary."""

    #: applied records between throttled cursor persists (the gates make
    #: a stale persisted cursor merely re-stream records, never re-apply)
    PERSIST_EVERY = 64

    def __init__(
        self,
        service,
        primary_address: str,
        *,
        reconnect_base: float = 0.2,
        reconnect_max: float = 5.0,
        state_store: Optional[ReplicaStateStore] = None,
        listen_address: Optional[str] = None,
        initial_cursor: Optional[int] = None,
        initial_log_id: Optional[str] = None,
    ):
        self.service = service
        self.primary_address = primary_address
        self.reconnect_base = reconnect_base
        self.reconnect_max = reconnect_max
        #: replica-side cursor persistence (ISSUE 4 satellite)
        self.state_store = state_store
        #: this replica's announced serving address (sentinel discovery)
        self.listen_address = listen_address
        #: last op seq fully applied (the reconnect cursor); None until
        #: the first successful sync
        self.cursor: Optional[int] = initial_cursor
        #: the primary log identity the cursor belongs to (Redis replid
        #: parity) — echoed on reconnect; a primary whose log identity
        #: changed (rewound/recreated) answers with a full resync
        self.log_id: Optional[str] = initial_log_id
        self.head_seq = 0
        self.link = "connecting"
        self.full_syncs = 0
        self.partial_syncs = 0
        self.records_applied = 0
        self.records_skipped = 0
        self.last_sync_kind: Optional[str] = None
        self._since_persist = 0
        self._stop = threading.Event()
        self._call = None
        self._call_lock = locks.named_lock("repl.applier_call")
        #: live ReplAck sender (sync-repl, ISSUE 5); rebuilt per sync
        self._ack: Optional[_AckSender] = None
        self._channel = None
        self._thread = threading.Thread(
            target=self._run, name="tpubloom-replica", daemon=True
        )
        service.replica_applier = self
        service.primary_address = primary_address
        #: from here on the local op log (if any) is fed by reappend —
        #: handler-side appends would mint conflicting seqs
        service._stream_fed = True
        # crash-forensics black box (ISSUE 18 satellite): replicas used
        # to arm the PR-16 rings only when the server ENTRYPOINT had a
        # log/ckpt dir to pass along — an in-process chaos replica
        # (test_repl / test_sync_repl) carries a state store but never
        # runs that entrypoint, so its post-mortem rings did not exist.
        # Arm from whatever durable dir this replica already owns; the
        # box is process-global, so never steal one another configure()
        # claimed (the replica's records still land in THAT ring), and
        # only stamp node identity on the ring we armed ourselves —
        # overwriting a co-hosted primary's meta would misattribute its
        # post-mortem timeline.
        state_dir = None
        if state_store is not None:
            state_dir = state_store.directory
        elif service.oplog is not None:
            state_dir = getattr(service.oplog, "directory", None)
        if state_dir is not None and not obs_blackbox.enabled():
            obs_blackbox.configure(
                state_dir,
                node={
                    k: v
                    for k, v in {
                        "role": "replica",
                        "addr": listen_address,
                        "primary": primary_address,
                    }.items()
                    if v is not None
                },
            )

    def start(self) -> "ReplicaApplier":
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        with self._call_lock:
            if self._call is not None:
                self._call.cancel()
            if self._ack is not None:
                self._ack.close()
                self._ack = None
        if self._thread.is_alive():
            self._thread.join(timeout=timeout)
        self._persist_cursor(force=True)

    def _persist_cursor(self, force: bool = False) -> None:
        """Throttled write of the resume point (every PERSIST_EVERY
        applied records + every sync transition + on stop): staler only
        costs re-streamed records — the seq gates absorb them."""
        if self.state_store is None or self.cursor is None:
            return
        self._since_persist += 1
        if force or self._since_persist >= self.PERSIST_EVERY:
            self._since_persist = 0
            try:
                self.state_store.store(self.cursor, self.log_id)
            except OSError:
                log.exception("repl cursor persist failed (non-fatal)")

    def status(self) -> dict:
        return {
            "primary": self.primary_address,
            "link": self.link,
            "cursor": self.cursor,
            "log_id": self.log_id,
            "head_seq": self.head_seq,
            "lag_seq": max(0, self.head_seq - (self.cursor or 0)),
            "full_syncs": self.full_syncs,
            "partial_syncs": self.partial_syncs,
            "records_applied": self.records_applied,
            "records_skipped": self.records_skipped,
            "sync_repl": self._ack is not None and not self._ack.broken,
        }

    def wait_caught_up(self, timeout: float = 30.0, poll: float = 0.02) -> bool:
        """Test/operator helper: block until lag_seq == 0 after at least
        one successful sync. NOTE: ``head_seq`` is the newest seq the
        *replica has heard of* — a write committed on the primary a
        moment ago may not be in it yet; to wait for a specific write
        use :meth:`wait_for_seq` with the primary's log seq."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if (
                self.cursor is not None
                and self.link == "connected"
                and self.head_seq <= self.cursor
            ):
                return True
            time.sleep(poll)
        return False

    def wait_for_seq(self, seq: int, timeout: float = 30.0, poll: float = 0.02) -> bool:
        """Block until the replica has applied (or skipped as already
        contained) every record up to ``seq`` — the read-your-writes
        barrier: pass the primary's log seq after a write."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.cursor is not None and self.cursor >= seq:
                return True
            time.sleep(poll)
        return False

    # -- stream loop ---------------------------------------------------------

    def _run(self) -> None:
        attempt = 0
        while not self._stop.is_set():
            channel = grpc.insecure_channel(
                self.primary_address,
                options=[
                    ("grpc.max_receive_message_length", 256 * 1024 * 1024),
                ],
            )
            self._channel = channel
            stream_call = channel.unary_stream(
                protocol.method_path("ReplStream"),
                request_serializer=lambda b: b,
                response_deserializer=lambda b: b,
            )
            req: dict = {"caps": ["batch-zlib"]}
            if self.listen_address:
                req["listen"] = self.listen_address
            if self.cursor is not None:
                req["cursor"] = self.cursor
                req["log_id"] = self.log_id
            try:
                self.link = "connecting"
                call = stream_call(protocol.encode(req), timeout=None)
                with self._call_lock:
                    self._call = call
                for raw in call:
                    attempt = 0  # any delivered message resets backoff
                    self._handle(protocol.decode(raw))
                    if self._stop.is_set():
                        break
            except FullResyncNeeded as e:
                log.info(
                    "replication: %s — dropping cursor for a full resync", e
                )
                self.cursor = None
                attempt = 0
            except grpc.RpcError as e:
                if not self._stop.is_set():
                    code = getattr(e, "code", lambda: None)()
                    log.warning(
                        "replication stream to %s lost (%s); reconnecting",
                        self.primary_address, code,
                    )
            except Exception:
                log.exception("replication apply failed; reconnecting")
                # ISSUE 19 satellite: a replica that cannot apply what
                # its primary sent is a fail-stop in miniature — freeze
                # both black-box rings NOW, before minutes of reconnect
                # churn lap the records that explain the bad apply
                obs_blackbox.snapshot_rings("replica-failstop")
            finally:
                with self._call_lock:
                    self._call = None
                    # the ack stream rides this channel — tear it down
                    # with the sync stream; the next sync re-opens it
                    # under its fresh session id
                    if self._ack is not None:
                        self._ack.close()
                        self._ack = None
                channel.close()
                self._channel = None
            if self._stop.is_set():
                break
            self.link = "lost"
            _counters.incr("repl_reconnects")
            delay = min(
                self.reconnect_max, self.reconnect_base * (2 ** attempt)
            ) * (0.5 + random.random())
            attempt += 1
            self._stop.wait(delay)
        self.link = "stopped"

    def _handle(self, msg: dict) -> None:
        kind = msg.get("kind")
        if kind == "full_sync_begin":
            self.link = "syncing"
            self.last_sync_kind = "full"
            self.full_syncs += 1
            self.head_seq = msg["seq"]
            self._sync_filters = list(msg.get("filters", ()))
        elif kind == "snapshot":
            self.service.install_snapshot(
                msg["name"], msg["blob"], msg["applied_seq"]
            )
        elif kind == "full_sync_end":
            # drop local filters the primary no longer has — a full
            # resync is a state reset, not a merge
            self.service.retain_only(self._sync_filters)
            self.cursor = msg["cursor"]
            self.log_id = msg.get("log_id")
            if self.service.oplog is not None:
                # chained: the local log's history is no longer a prefix
                # of anything real — wipe it, restart the seq space at
                # the resync cursor, rotate its identity so downstream
                # cursors full-resync too (their state reset with ours)
                self.service.oplog.reset_to(self.cursor)
            self._adopt_epoch(msg)
            # gauge before link flips: wait_caught_up gates on
            # link == "connected", and callers read repl_lag_seq the
            # moment it returns — _start_ack below can take a while
            _counters.set_gauge(
                "repl_lag_seq", max(0, self.head_seq - (self.cursor or 0))
            )
            self.link = "connected"
            self._persist_cursor(force=True)
            self._start_ack(msg)
        elif kind == "partial_sync":
            self.last_sync_kind = "partial"
            self.partial_syncs += 1
            self.cursor = msg["cursor"]
            self.log_id = msg.get("log_id")
            self._adopt_epoch(msg)
            _counters.set_gauge(
                "repl_lag_seq", max(0, self.head_seq - (self.cursor or 0))
            )
            self.link = "connected"
            self._persist_cursor(force=True)
            self._start_ack(msg)
        elif kind == "record":
            self._handle_record(msg)
        elif kind == "records":
            # coalesced+compressed frame (negotiated "batch-zlib" cap)
            records = msgpack.unpackb(
                zlib.decompress(msg["z"]), raw=False
            )
            _counters.incr("repl_batched_frames_received")
            for rec in records:
                self._handle_record(rec)
        elif kind == "heartbeat":
            self.head_seq = max(self.head_seq, msg["seq"])
            self._adopt_epoch(msg)
            if self.cursor is not None and self.head_seq <= self.cursor:
                _counters.set_gauge("repl_lag_seconds", 0.0)
            with self._call_lock:
                if self._ack is not None and self._ack.broken:
                    # the primary (or an injected repl.ack_recv) killed
                    # the ack stream: re-open it under the same session
                    # and re-send the current cursor
                    _counters.incr("repl_ack_stream_reopened")
                    sid = self._ack.sid
                    self._ack.close()
                    self._ack = None
                    if self._channel is not None:
                        self._ack = _AckSender(self._channel, sid)
                        if self.cursor is not None:
                            self._ack.update(self.cursor)
        elif kind == "error":
            raise protocol.BloomServiceError(
                msg.get("code", "UNKNOWN"), msg.get("message", "")
            )
        _counters.set_gauge(
            "repl_lag_seq", max(0, self.head_seq - (self.cursor or 0))
        )

    def _start_ack(self, msg: dict) -> None:
        """(Re)open the ReplAck stream for the session id the sync frame
        announced; primaries predating sync-repl send no ``sid`` and get
        no acks (they have no barrier to feed either)."""
        sid = msg.get("sid")
        with self._call_lock:
            if self._ack is not None:
                self._ack.close()
                self._ack = None
            if sid is None or self._channel is None:
                return
            self._ack = _AckSender(self._channel, int(sid))
            if self.cursor is not None:
                # the sync point itself is applied state — ack it now so
                # a quorum blocked on pre-sync records unblocks without
                # waiting for the next record
                self._ack.update(self.cursor)

    def _adopt_epoch(self, msg: dict) -> None:
        """Sync/heartbeat frames carry the primary's topology epoch —
        replicas learn it passively, so a bare replica still fences
        stale ``Promote``/``ReplicaOf`` requests correctly."""
        epoch = msg.get("epoch")
        if epoch:
            self.service.adopt_epoch(int(epoch))

    def _handle_record(self, rec: dict) -> None:
        """One op record: re-append to the local log first when chained
        (write-ahead — replay is idempotent, a logged-but-unapplied
        record is healed by the seq gates at restart), then apply."""
        if self.service.oplog is not None:
            try:
                self.service.reappend_record(rec)
            except ValueError as e:
                # seq gap against the local log: only a full resync can
                # restore a coherent prefix — never paper over a gap
                raise FullResyncNeeded("<oplog>", reason=str(e))
        # distributed tracing (ISSUE 15): the apply is stamped with the
        # ORIGIN rid — the same trace id the client's hop, the server's
        # handler and the coalescer's flush used — so a cross-node
        # assembly shows where the record landed. Captured when the
        # record carries the forced flag (_log_op stamps it for sampled
        # requests and traced flushes), this node's own deterministic
        # rid sample hits, or — the ISSUE-16 satellite, same rule the
        # server wrapper applies — the apply turns out SLOWLOG-WORTHY:
        # an unsampled record whose apply would enter this replica's
        # slowlog gets its span anyway, so the slow tail of the apply
        # path traces like the slow tail of the serve path. Timing runs
        # whenever the ring is armed, because the slow decision needs
        # the duration first.
        measured = obs_trace.enabled() and bool(rec.get("rid"))
        forced = False
        captured = False
        parent = None
        if measured:
            req_trace = (rec.get("req") or {}).get("trace")
            if isinstance(req_trace, dict):
                forced = bool(req_trace.get("forced"))
                captured = forced
                p = req_trace.get("span")
                parent = p if isinstance(p, str) else None
            else:
                captured = obs_trace.hit(rec["rid"])
        w0 = time.time() if measured else 0.0
        t0 = time.perf_counter() if measured else 0.0
        applied = self.service.apply_record(rec)
        if measured:
            duration_s = time.perf_counter() - t0
            # the probe (a slowlog lock round trip) only matters when
            # the record is not already captured
            slow = not captured and self.service.slowlog.would_record(
                duration_s
            )
            if captured or slow:
                obs_trace.record_span(
                    "repl.apply",
                    rid=rec["rid"],
                    parent=parent,
                    start=w0,
                    duration_s=duration_s,
                    attrs={
                        "seq": int(rec["seq"]),
                        "method": rec.get("method"),
                        "filter": (rec.get("req") or {}).get("name"),
                        "applied": bool(applied),
                    },
                    # forced and slowlog-worthy applies persist to the
                    # black box (ISSUE 16) — a replica killed mid-apply
                    # leaves the spans that explain what it was doing
                    spill=forced or slow,
                )
        if applied:
            self.records_applied += 1
            _counters.incr("repl_records_applied")
        else:
            self.records_skipped += 1
            _counters.incr("repl_records_skipped")
        self.head_seq = max(self.head_seq, rec["seq"])
        # gauge BEFORE the cursor advance: wait_caught_up polls the
        # cursor from another thread, and callers assert repl_lag_seq
        # the moment it flips — the gauge must already agree
        _counters.set_gauge(
            "repl_lag_seq", max(0, self.head_seq - rec["seq"])
        )
        self.cursor = rec["seq"]
        ack = self._ack
        if ack is not None:
            ack.update(rec["seq"])
        self._persist_cursor()
        _counters.set_gauge(
            "repl_lag_seconds", max(0.0, time.time() - rec.get("ts", 0))
        )
