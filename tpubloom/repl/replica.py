"""Replica side: consume a primary's ``ReplStream`` and apply it.

``python -m tpubloom.server --replica-of host:port`` runs the normal
server read-only (writes get ``READONLY``, Redis parity) with one
:class:`ReplicaApplier` thread behind it:

* **sync** — first contact sends no cursor → full resync (snapshot blobs
  install via :meth:`BloomService.install_snapshot`, then the log tail);
  reconnects send the last fully-applied seq → partial resync when the
  primary still has the tail, a fresh full resync otherwise.
* **idempotent apply** — every record is gated twice: the stream-global
  cursor (records at or below it are never re-requested) and the
  per-filter ``applied_seq`` (a record already contained in an installed
  snapshot is skipped, counted in ``repl_records_skipped``). Killing the
  stream mid-batch and reconnecting therefore re-applies nothing — the
  chaos suite pins this with the ``repl.stream_send``/``repl.apply``
  fault points.
* **lag** — ``repl_lag_seq`` (head seq from records/heartbeats minus the
  applied cursor) and ``repl_lag_seconds`` (apply-time minus the
  record's primary commit time; 0 when caught up on a heartbeat).
* **liveness** — transport errors back off exponentially
  (``repl_reconnects``); the link state lands in Health via
  :meth:`status` (``link: connected/connecting/lost``).
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Optional

import grpc

from tpubloom.obs import counters as _counters
from tpubloom.server import protocol

log = logging.getLogger("tpubloom.repl")


class FullResyncNeeded(Exception):
    """Raised by the apply path when a record's effect cannot be derived
    from the stream alone — e.g. a ``CreateFilter`` that bootstrapped
    state from a checkpoint the replica does not have. The applier drops
    its cursor and reconnects: the full-resync snapshot carries the
    state the record could not."""

    def __init__(self, name: str):
        super().__init__(
            f"record for filter {name!r} references state only a full "
            f"resync can transfer"
        )
        self.name = name


class ReplicaApplier:
    """Background thread that keeps a local (read-only) service in sync
    with a primary."""

    def __init__(
        self,
        service,
        primary_address: str,
        *,
        reconnect_base: float = 0.2,
        reconnect_max: float = 5.0,
    ):
        self.service = service
        self.primary_address = primary_address
        self.reconnect_base = reconnect_base
        self.reconnect_max = reconnect_max
        #: last op seq fully applied (the reconnect cursor); None until
        #: the first successful sync
        self.cursor: Optional[int] = None
        #: the primary log identity the cursor belongs to (Redis replid
        #: parity) — echoed on reconnect; a primary whose log identity
        #: changed (rewound/recreated) answers with a full resync
        self.log_id: Optional[str] = None
        self.head_seq = 0
        self.link = "connecting"
        self.full_syncs = 0
        self.partial_syncs = 0
        self.records_applied = 0
        self.records_skipped = 0
        self.last_sync_kind: Optional[str] = None
        self._stop = threading.Event()
        self._call = None
        self._call_lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._run, name="tpubloom-replica", daemon=True
        )
        service.replica_applier = self
        service.primary_address = primary_address

    def start(self) -> "ReplicaApplier":
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        with self._call_lock:
            if self._call is not None:
                self._call.cancel()
        self._thread.join(timeout=timeout)

    def status(self) -> dict:
        return {
            "primary": self.primary_address,
            "link": self.link,
            "cursor": self.cursor,
            "log_id": self.log_id,
            "head_seq": self.head_seq,
            "lag_seq": max(0, self.head_seq - (self.cursor or 0)),
            "full_syncs": self.full_syncs,
            "partial_syncs": self.partial_syncs,
            "records_applied": self.records_applied,
            "records_skipped": self.records_skipped,
        }

    def wait_caught_up(self, timeout: float = 30.0, poll: float = 0.02) -> bool:
        """Test/operator helper: block until lag_seq == 0 after at least
        one successful sync. NOTE: ``head_seq`` is the newest seq the
        *replica has heard of* — a write committed on the primary a
        moment ago may not be in it yet; to wait for a specific write
        use :meth:`wait_for_seq` with the primary's log seq."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if (
                self.cursor is not None
                and self.link == "connected"
                and self.head_seq <= self.cursor
            ):
                return True
            time.sleep(poll)
        return False

    def wait_for_seq(self, seq: int, timeout: float = 30.0, poll: float = 0.02) -> bool:
        """Block until the replica has applied (or skipped as already
        contained) every record up to ``seq`` — the read-your-writes
        barrier: pass the primary's log seq after a write."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.cursor is not None and self.cursor >= seq:
                return True
            time.sleep(poll)
        return False

    # -- stream loop ---------------------------------------------------------

    def _run(self) -> None:
        attempt = 0
        while not self._stop.is_set():
            channel = grpc.insecure_channel(
                self.primary_address,
                options=[
                    ("grpc.max_receive_message_length", 256 * 1024 * 1024),
                ],
            )
            stream_call = channel.unary_stream(
                protocol.method_path("ReplStream"),
                request_serializer=lambda b: b,
                response_deserializer=lambda b: b,
            )
            req: dict = {}
            if self.cursor is not None:
                req["cursor"] = self.cursor
                req["log_id"] = self.log_id
            try:
                self.link = "connecting"
                call = stream_call(protocol.encode(req), timeout=None)
                with self._call_lock:
                    self._call = call
                for raw in call:
                    attempt = 0  # any delivered message resets backoff
                    self._handle(protocol.decode(raw))
                    if self._stop.is_set():
                        break
            except FullResyncNeeded as e:
                log.info(
                    "replication: %s — dropping cursor for a full resync", e
                )
                self.cursor = None
                attempt = 0
            except grpc.RpcError as e:
                if not self._stop.is_set():
                    code = getattr(e, "code", lambda: None)()
                    log.warning(
                        "replication stream to %s lost (%s); reconnecting",
                        self.primary_address, code,
                    )
            except Exception:
                log.exception("replication apply failed; reconnecting")
            finally:
                with self._call_lock:
                    self._call = None
                channel.close()
            if self._stop.is_set():
                break
            self.link = "lost"
            _counters.incr("repl_reconnects")
            delay = min(
                self.reconnect_max, self.reconnect_base * (2 ** attempt)
            ) * (0.5 + random.random())
            attempt += 1
            self._stop.wait(delay)
        self.link = "stopped"

    def _handle(self, msg: dict) -> None:
        kind = msg.get("kind")
        if kind == "full_sync_begin":
            self.link = "syncing"
            self.last_sync_kind = "full"
            self.full_syncs += 1
            self.head_seq = msg["seq"]
            self._sync_filters = list(msg.get("filters", ()))
        elif kind == "snapshot":
            self.service.install_snapshot(
                msg["name"], msg["blob"], msg["applied_seq"]
            )
        elif kind == "full_sync_end":
            # drop local filters the primary no longer has — a full
            # resync is a state reset, not a merge
            self.service.retain_only(self._sync_filters)
            self.cursor = msg["cursor"]
            self.log_id = msg.get("log_id")
            self.link = "connected"
        elif kind == "partial_sync":
            self.last_sync_kind = "partial"
            self.partial_syncs += 1
            self.cursor = msg["cursor"]
            self.log_id = msg.get("log_id")
            self.link = "connected"
        elif kind == "record":
            applied = self.service.apply_record(msg)
            if applied:
                self.records_applied += 1
                _counters.incr("repl_records_applied")
            else:
                self.records_skipped += 1
                _counters.incr("repl_records_skipped")
            self.cursor = msg["seq"]
            self.head_seq = max(self.head_seq, msg["seq"])
            _counters.set_gauge(
                "repl_lag_seconds", max(0.0, time.time() - msg.get("ts", 0))
            )
        elif kind == "heartbeat":
            self.head_seq = max(self.head_seq, msg["seq"])
            if self.cursor is not None and self.head_seq <= self.cursor:
                _counters.set_gauge("repl_lag_seconds", 0.0)
        elif kind == "error":
            raise protocol.BloomServiceError(
                msg.get("code", "UNKNOWN"), msg.get("message", "")
            )
        _counters.set_gauge(
            "repl_lag_seq", max(0, self.head_seq - (self.cursor or 0))
        )
