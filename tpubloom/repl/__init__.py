"""Replication & changefeed subsystem (ISSUE 3).

The reference gem's durability/scale-out story is Redis's: an
append-only op log (AOF), primary→replica streaming (PSYNC), read-only
replicas (``READONLY``), and the ``MONITOR`` firehose. This package is
that story for tpubloom:

* :mod:`tpubloom.repl.record` — CRC32C-framed op records (one per
  mutating RPC, with seq + rid for idempotent replay);
* :mod:`tpubloom.repl.log` — the segmented append-only op log:
  crash-recovery with torn-tail truncation, checkpoint-keyed
  truncation, tailing for live streams;
* :mod:`tpubloom.repl.primary` — the ``ReplStream`` RPC: full resync
  (filter snapshots + tail) or partial resync (cursor still in the
  log), heartbeats, connected-replica accounting;
* :mod:`tpubloom.repl.replica` — the applier behind
  ``--replica-of host:port``: sync, seq-gated idempotent apply,
  reconnect with backoff, lag gauges;
* :mod:`tpubloom.repl.monitor` — the ``Monitor`` RPC (MONITOR parity):
  live per-filter-filterable op stream off the same commit points.

Wiring lives in :mod:`tpubloom.server.service` (log appends at commit
points, startup replay, read-only mode) and
:mod:`tpubloom.server.client` (read-preference routing, READONLY-aware
fallback).
"""

from tpubloom.repl.log import OpLog
from tpubloom.repl.monitor import MonitorHub, monitor_stream
from tpubloom.repl.primary import ReplicaSessions, repl_stream
from tpubloom.repl.record import decode_record, encode_record, scan_buffer
from tpubloom.repl.replica import (
    ReplicaApplier,
    ReplicaStateStore,
    bootstrap_from_local,
)

__all__ = [
    "OpLog",
    "MonitorHub",
    "monitor_stream",
    "ReplicaSessions",
    "repl_stream",
    "ReplicaApplier",
    "ReplicaStateStore",
    "bootstrap_from_local",
    "decode_record",
    "encode_record",
    "scan_buffer",
]
