"""MONITOR-parity live op stream (the ROADMAP PR-1 follow-up).

Redis ``MONITOR`` turns a connection into a firehose of every command
the server executes. Here the equivalent is the server-streaming
``Monitor`` RPC: the RPC wrapper publishes one event per finished
request into this hub, and each subscriber drains its own bounded queue
— a slow monitor client loses *its own* oldest events (counted in
``monitor_events_dropped``) instead of back-pressuring the data plane,
which is strictly better than Redis (a slow MONITOR client grows the
server's output buffer until the server kills it).

Subscriptions optionally filter by filter name (``{"name": "urls"}``),
which Redis MONITOR cannot do — the per-key-namespace view falls out of
having structured events instead of raw command text.

Event shape: ``{"kind": "op", "ts", "method", "name", "rid", "batch",
"duration_s", "ok"}``. The stream opens with ``{"kind": "hello"}`` (the
``+OK`` MONITOR ack — subscribers know they are live before the first
event) and idles with ``{"kind": "heartbeat"}`` ticks.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from typing import Optional

from tpubloom.obs import counters as _counters
from tpubloom.utils import locks

#: Per-subscriber buffered events before drop-oldest kicks in.
DEFAULT_QUEUE_DEPTH = 1024


class MonitorHub:
    """Fan-out of op events to bounded per-subscriber queues."""

    def __init__(self, queue_depth: int = DEFAULT_QUEUE_DEPTH):
        self.queue_depth = queue_depth
        self._lock = locks.named_lock("repl.monitor_hub")
        self._ids = itertools.count()
        #: sub id -> (queue, name filter or None)
        self._subs: dict[int, tuple["queue.Queue", Optional[str]]] = {}

    @property
    def active(self) -> bool:
        """Cheap pre-check so the RPC wrapper pays one attribute read per
        request while nobody is monitoring."""
        return bool(self._subs)

    def subscribe(self, name: Optional[str] = None) -> int:
        q: "queue.Queue" = queue.Queue(maxsize=self.queue_depth)
        with self._lock:
            sid = next(self._ids)
            self._subs[sid] = (q, name)
        _counters.set_gauge("monitor_subscribers", len(self._subs))
        return sid

    def unsubscribe(self, sid: int) -> None:
        with self._lock:
            self._subs.pop(sid, None)
        _counters.set_gauge("monitor_subscribers", len(self._subs))

    def get(self, sid: int, timeout: float) -> Optional[dict]:
        with self._lock:
            entry = self._subs.get(sid)
        if entry is None:
            return None
        try:
            return entry[0].get(timeout=timeout)
        except queue.Empty:
            return None

    def publish(self, event: dict) -> None:
        """Deliver to every matching subscriber; never blocks the caller
        (drop-oldest per subscriber on overflow)."""
        with self._lock:
            subs = list(self._subs.values())
        for q, name in subs:
            if name is not None and event.get("name") != name:
                continue
            while True:
                try:
                    q.put_nowait(event)
                    break
                except queue.Full:
                    try:
                        q.get_nowait()
                        _counters.incr("monitor_events_dropped")
                    except queue.Empty:
                        pass


def monitor_stream(service, req: dict, context, *, heartbeat_s: float = 1.0):
    """Generator behind the ``Monitor`` RPC: hello, then ops as they
    happen, heartbeats while idle; ends when the client cancels or the
    server drains."""
    hub: MonitorHub = service.monitor_hub
    sid = hub.subscribe(req.get("name") or None)
    try:
        yield {"kind": "hello", "ts": time.time(), "filter": req.get("name")}
        while context.is_active() and not service.draining:
            event = hub.get(sid, timeout=heartbeat_s)
            if event is not None:
                yield event
            else:
                yield {"kind": "heartbeat", "ts": time.time()}
    finally:
        hub.unsubscribe(sid)
