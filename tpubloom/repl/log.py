"""Append-only op log — the AOF-parity durability + replication backbone.

The reference gem inherits Redis's durability story: every mutating
command lands in the AOF, a restart replays it, and the same byte stream
feeds primary→replica links. This is that machinery for tpubloom:

* **append** — one CRC32C-framed record per committed mutating RPC
  (:mod:`tpubloom.repl.record`), written+flushed under the log lock so a
  concurrent reader never observes a half-record except at a crash-torn
  tail. Default fsync policy is the OS page cache (Redis
  ``appendfsync no`` parity; pass ``fsync=True`` for ``always``).
* **segments** — the log rolls into ``oplog.<first_seq>.seg`` files
  every ``segment_bytes``; checkpoint-keyed truncation
  (:meth:`OpLog.truncate_to`) drops whole segments whose every record is
  already covered by a landed checkpoint generation on every filter —
  the log only ever holds the replay *tail*, like an AOF after rewrite.
* **recovery** — on open, every segment is scanned through the record
  CRCs; a torn tail (crash mid-append) is truncated back to the last
  intact record (``aof-load-truncated yes`` parity) and counted in
  ``repl_log_torn_tail_truncated``. Corruption in a *non*-tail position
  drops everything from that point (a gap must not be replayed past).
* **tailing** — :meth:`wait_for` blocks stream generators until a seq
  exists; appends notify. Readers (:meth:`read_from`) re-open segment
  files read-only, so slow replicas never hold the append lock.

Fault point ``repl.append`` (:mod:`tpubloom.faults`) fires inside the
append lock, before any bytes are written.
"""

from __future__ import annotations

import logging
import os
import re
import threading
import time
from typing import Iterator, Optional

from tpubloom import faults
from tpubloom.obs import counters as _counters
from tpubloom.repl import record as rec
from tpubloom.utils import locks

log = logging.getLogger("tpubloom.repl")

_SEG_RE = re.compile(r"^oplog\.(?P<start>\d{20})\.seg$")

DEFAULT_SEGMENT_BYTES = 4 << 20


class OpLog:
    """Segmented append-only log of mutating ops; thread-safe."""

    def __init__(
        self,
        directory: str,
        *,
        segment_bytes: int = DEFAULT_SEGMENT_BYTES,
        fsync: bool = False,
        start_seq: int = 0,
    ):
        """``start_seq`` seeds an EMPTY log's sequence space (promotion:
        a replica adopting the op log opens a fresh log at its applied
        seq so downstream cursors stay meaningful); ignored when the
        directory already holds records."""
        self.directory = directory
        self.segment_bytes = segment_bytes
        self.fsync = fsync
        os.makedirs(directory, exist_ok=True)
        self._cond = locks.named_condition("repl.oplog")
        self._fh = None
        self._size = 0
        self._bytes = 0
        self._closed = False
        #: [(start_seq, path)] oldest→newest; the last one is active
        self._segments: list[tuple[int, str]] = []
        self.last_seq = 0
        rewound = self._recover()
        if not self._segments and start_seq > self.last_seq:
            self.last_seq = start_seq
        self._bytes = sum(
            os.path.getsize(p) for _, p in self._segments if os.path.exists(p)
        )
        #: replication identity (Redis replid parity): replicas pin their
        #: cursor to this id, and a mismatch forces a full resync. The id
        #: persists across clean restarts but ROTATES whenever recovery
        #: had to truncate/drop records — the seq space rewound, so an
        #: old cursor would silently swallow new records.
        self.log_id = self._load_log_id(rotate=rewound)
        #: PSYNC2-parity secondary identity (Redis replid2): after a
        #: promotion, cursors pinned to the PREVIOUS primary's log id are
        #: still resumable up to ``alias_upto`` — the promoted node's log
        #: holds the same records in the same seq space up to that point.
        self.alias_id: Optional[str] = None
        self.alias_upto = 0
        if rewound:
            self._drop_alias()
        else:
            self._load_alias()
        self._update_gauges()

    def _load_log_id(self, rotate: bool) -> str:
        import secrets

        path = os.path.join(self.directory, "oplog.id")
        if not rotate:
            try:
                with open(path) as f:
                    existing = f.read().strip()
                if existing:
                    return existing
            except OSError:
                pass
        new_id = secrets.token_hex(16)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(new_id)
        os.replace(tmp, path)
        return new_id

    # -- identity alias (failover continuity, Redis replid2 parity) ----------

    def _alias_path(self) -> str:
        return os.path.join(self.directory, "oplog.alias.json")

    def _load_alias(self) -> None:
        import json

        try:
            with open(self._alias_path()) as f:
                data = json.load(f)
            self.alias_id = data["log_id"] or None
            self.alias_upto = int(data["upto"])
        except (OSError, ValueError, KeyError, TypeError):
            self.alias_id, self.alias_upto = None, 0

    def _drop_alias(self) -> None:
        self.alias_id, self.alias_upto = None, 0
        try:
            os.unlink(self._alias_path())
        except OSError:
            pass

    def set_alias(self, log_id: Optional[str], upto: int) -> None:
        """Remember that this log's records up to ``upto`` are identical
        to log identity ``log_id`` (the upstream a promoted replica was
        following) — cursors pinned to that id partial-resync instead of
        paying a full resync after failover."""
        import json

        if not log_id:
            return
        self.alias_id, self.alias_upto = log_id, int(upto)
        tmp = self._alias_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"log_id": log_id, "upto": int(upto)}, f)
        os.replace(tmp, self._alias_path())

    def resumable(self, cursor: int, log_id: Optional[str]) -> bool:
        """True iff a replica at ``(cursor, log_id)`` can partial-resync
        from this log: the identity matches (directly, or through the
        post-promotion alias within its validity window) AND every record
        past the cursor is still on disk."""
        with self._cond:
            if log_id == self.log_id:
                pass
            elif (
                self.alias_id is not None
                and log_id == self.alias_id
                and cursor <= self.alias_upto
            ):
                pass
            else:
                return False
        return self.has_cursor(cursor)

    # -- recovery ------------------------------------------------------------

    def _seg_path(self, start_seq: int) -> str:
        return os.path.join(self.directory, f"oplog.{start_seq:020d}.seg")

    def _recover(self) -> bool:
        """Scan + repair all segments; True iff any records were lost
        (torn tail truncated / corrupt tail dropped) — i.e. the seq
        space rewound and the log identity must rotate."""
        rewound = False
        found = sorted(
            (int(m.group("start")), os.path.join(self.directory, fn))
            for fn in os.listdir(self.directory)
            if (m := _SEG_RE.match(fn))
        )
        for i, (start, path) in enumerate(found):
            with open(path, "rb") as f:
                buf = f.read()
            records, valid_len, clean = rec.scan_buffer(buf)
            if not clean:
                rewound = True
                if i == len(found) - 1:
                    # crash-torn tail of the newest segment: drop the
                    # partial record, keep everything before it
                    log.warning(
                        "op log %s: torn tail, truncating %d -> %d bytes",
                        path, len(buf), valid_len,
                    )
                    _counters.incr("repl_log_torn_tail_truncated")
                    with open(path, "r+b") as f:
                        f.truncate(valid_len)
                else:
                    # mid-log corruption: records past the gap cannot be
                    # replayed safely — drop this tail and every later
                    # segment (bounded loss, never a silent gap)
                    log.error(
                        "op log %s: corrupt mid-log at byte %d; dropping "
                        "the tail and %d later segment(s)",
                        path, valid_len, len(found) - i - 1,
                    )
                    _counters.incr("repl_log_corrupt_dropped")
                    with open(path, "r+b") as f:
                        f.truncate(valid_len)
                    for _, later in found[i + 1 :]:
                        os.unlink(later)
                    found = found[: i + 1]
            self._segments.append((start, path))
            if records:
                self.last_seq = records[-1]["seq"]
            else:
                self.last_seq = max(self.last_seq, start - 1)
            if not clean:
                break
        if self._segments:
            active = self._segments[-1][1]
            self._size = os.path.getsize(active)
            self._fh = open(active, "ab")
        return rewound

    # -- write side ----------------------------------------------------------

    def append(self, method: str, req: dict, rid: Optional[str] = None) -> int:
        """Commit one op to the log; returns its seq. Raises if the log
        is closed or an armed ``repl.append`` fault fires."""
        with self._cond:
            if self._closed:
                raise RuntimeError("op log is closed")
            faults.fire("repl.append")
            seq = self.last_seq + 1
            frame = rec.encode_record(
                {
                    "seq": seq,
                    "method": method,
                    "rid": rid,
                    "req": req,
                    "ts": time.time(),
                }
            )
            if self._fh is None or self._size >= self.segment_bytes:
                self._roll(seq)
            self._fh.write(frame)
            self._fh.flush()  # lint: allow(blocking-under-lock): append IO under the log lock IS the commit protocol — readers may only ever observe whole records
            if self.fsync:
                os.fsync(self._fh.fileno())  # lint: allow(blocking-under-lock): appendfsync-always parity — durability before visibility is the point of the flag
            self._size += len(frame)
            self._bytes += len(frame)
            self.last_seq = seq
            self._cond.notify_all()
            self._update_gauges_locked()
        return seq

    def append_record(self, record: dict) -> bool:
        """Re-append one already-sequenced record VERBATIM (chained
        replicas: the upstream's seq space IS this log's seq space, which
        is what makes promoting a mid-chain node cheap). Returns False
        when the record is already in the log (partial-resync overlap);
        raises on a sequence gap — the caller must full-resync, a gap
        must never be papered over."""
        with self._cond:
            if self._closed:
                raise RuntimeError("op log is closed")
            seq = int(record["seq"])
            if seq <= self.last_seq:
                return False
            if seq != self.last_seq + 1:
                raise ValueError(
                    f"op log gap: re-append of seq {seq} onto last_seq "
                    f"{self.last_seq}"
                )
            frame = rec.encode_record(record)
            if self._fh is None or self._size >= self.segment_bytes:
                self._roll(seq)
            self._fh.write(frame)
            self._fh.flush()  # lint: allow(blocking-under-lock): append IO under the log lock IS the commit protocol — readers may only ever observe whole records
            if self.fsync:
                os.fsync(self._fh.fileno())  # lint: allow(blocking-under-lock): appendfsync-always parity — durability before visibility is the point of the flag
            self._size += len(frame)
            self._bytes += len(frame)
            self.last_seq = seq
            self._cond.notify_all()
            self._update_gauges_locked()
        return True

    def reset_to(self, seq: int) -> None:
        """Full-resync state reset: drop EVERY record, restart the seq
        space at ``seq``, and rotate the identity (this log's history is
        no longer a prefix of anything a downstream cursor could have
        followed)."""
        import secrets

        with self._cond:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
            for _, path in self._segments:
                try:
                    os.unlink(path)
                except OSError:
                    pass
            self._segments = []
            self._size = 0
            self._bytes = 0
            self.last_seq = int(seq)
            self.log_id = secrets.token_hex(16)
            tmp = os.path.join(self.directory, "oplog.id.tmp")
            with open(tmp, "w") as f:
                f.write(self.log_id)
            os.replace(tmp, os.path.join(self.directory, "oplog.id"))
            self._drop_alias()
            self._cond.notify_all()
            self._update_gauges_locked()

    def _roll(self, start_seq: int) -> None:
        """Start a new segment whose first record will be ``start_seq``
        (caller holds the lock)."""
        if self._fh is not None:
            self._fh.close()
        path = self._seg_path(start_seq)
        self._fh = open(path, "ab")
        self._size = 0
        self._segments.append((start_seq, path))

    # -- read side -----------------------------------------------------------

    @property
    def first_seq(self) -> int:
        """Oldest seq still available (== next seq when the log is
        empty/fully truncated). A cursor C supports a partial resync iff
        ``C + 1 >= first_seq``."""
        with self._cond:
            if self._segments:
                return self._segments[0][0]
            return self.last_seq + 1

    def has_cursor(self, cursor: int) -> bool:
        """True iff every record after ``cursor`` is still in the log."""
        return cursor + 1 >= self.first_seq

    def read_from(
        self, cursor: int, limit: Optional[int] = None
    ) -> Iterator[dict]:
        """Yield records with ``seq > cursor`` in order (up to ``limit``).

        Reads from snapshot state via fresh read-only handles; appends
        running concurrently are either seen whole (append flushes under
        the lock) or not at all — a racing partial tail just ends the
        scan early and the next poll picks it up."""
        with self._cond:
            segments = list(self._segments)
        yielded = 0
        for i, (start, path) in enumerate(segments):
            nxt = segments[i + 1][0] if i + 1 < len(segments) else None
            if nxt is not None and nxt <= cursor + 1:
                continue  # every record in this segment is <= cursor
            try:
                with open(path, "rb") as f:
                    buf = f.read()
            except FileNotFoundError:
                continue  # truncated underneath us — records were <= safe seq
            records, _, _ = rec.scan_buffer(buf)
            for r in records:
                if r["seq"] <= cursor:
                    continue
                yield r
                yielded += 1
                if limit is not None and yielded >= limit:
                    return

    def wait_for(self, seq: int, timeout: Optional[float] = None) -> bool:
        """Block until ``last_seq >= seq`` (or the log closes); True iff
        the seq exists."""
        with self._cond:
            self._cond.wait_for(
                lambda: self.last_seq >= seq or self._closed, timeout
            )
            return self.last_seq >= seq

    # -- retention -----------------------------------------------------------

    def truncate_to(self, seq: int) -> int:
        """Drop whole segments whose every record has ``seq <=`` the given
        safe point (never the active segment); returns segments removed.

        The safe point is checkpoint-keyed by the caller: the min, over
        all filters, of the op seq the newest *landed* checkpoint
        generation covers — records at or below it are replayable from
        checkpoints alone."""
        removed = 0
        with self._cond:
            while len(self._segments) >= 2 and self._segments[1][0] <= seq + 1:
                _, path = self._segments.pop(0)
                try:
                    self._bytes -= os.path.getsize(path)
                    os.unlink(path)
                except OSError:
                    pass
                removed += 1
            if removed:
                self._bytes = max(0, self._bytes)
                self._update_gauges_locked()
        return removed

    # -- observability / lifecycle -------------------------------------------

    def total_bytes(self) -> int:
        """Incrementally-tracked log size (no per-call disk stats)."""
        with self._cond:
            return self._bytes

    def stats(self) -> dict:
        with self._cond:
            return {
                "first_seq": (
                    self._segments[0][0] if self._segments else self.last_seq + 1
                ),
                "last_seq": self.last_seq,
                "segments": len(self._segments),
                "bytes": self._bytes,
                "log_id": self.log_id,
            }

    def _update_gauges(self) -> None:
        with self._cond:
            self._update_gauges_locked()

    def _update_gauges_locked(self) -> None:
        _counters.set_gauge("repl_log_seq", self.last_seq)
        _counters.set_gauge("repl_log_bytes", self._bytes)
        _counters.set_gauge("repl_log_segments", len(self._segments))

    def follower(self, cursor: int) -> "LogFollower":
        """Incremental tail reader starting after ``cursor`` (what the
        stream generators use — polling costs O(new bytes), not
        O(segment))."""
        return LogFollower(self, cursor)

    def close(self) -> None:
        with self._cond:
            self._closed = True
            if self._fh is not None:
                self._fh.close()
                self._fh = None
            self._cond.notify_all()


class LogFollower:
    """Stateful reader over a live :class:`OpLog`: remembers its byte
    position (segment start seq + validated-record-boundary offset) so
    each poll reads only bytes appended since the last one. A partially
    flushed tail frame ends the scan at the last intact record and is
    re-read complete on the next poll; a segment truncated away under
    the follower degrades to :meth:`OpLog.read_from` (which skips to the
    surviving segments)."""

    def __init__(self, oplog: OpLog, cursor: int):
        self.oplog = oplog
        self.cursor = cursor
        self._seg_start: Optional[int] = None
        self._offset = 0

    def next_batch(self, limit: int = 256) -> list:
        """Records with ``seq > cursor``, up to ``limit``; advances the
        cursor past everything returned."""
        out: list = []
        while len(out) < limit:
            with self.oplog._cond:
                segments = list(self.oplog._segments)
            if not segments:
                break
            starts = [s for s, _ in segments]
            if self._seg_start is None or self._seg_start not in starts:
                # (re)position: one full scan via the skip logic, then
                # pin to the START of the segment holding the cursor —
                # the next incremental pass re-scans that one segment
                # (seq-filtered, so nothing duplicates) and lands on the
                # true byte boundary
                import bisect

                resync = list(self.oplog.read_from(self.cursor, limit=limit))
                for r in resync:
                    self.cursor = r["seq"]
                out.extend(resync)
                idx = bisect.bisect_right(starts, self.cursor + 1) - 1
                if idx >= 0:
                    self._seg_start = starts[idx]
                    self._offset = 0
                break
            idx = starts.index(self._seg_start)
            path = segments[idx][1]
            try:
                with open(path, "rb") as f:
                    f.seek(self._offset)
                    buf = f.read()
            except OSError:
                self._seg_start = None
                continue
            records, valid_len, _ = rec.scan_buffer(buf)
            self._offset += valid_len
            fresh = [r for r in records if r["seq"] > self.cursor]
            for r in fresh:
                self.cursor = r["seq"]
            out.extend(fresh)
            if records or idx == len(segments) - 1:
                break
            # this segment is exhausted AND a newer one exists: the log
            # rolled — move to the next segment from its start
            self._seg_start = starts[idx + 1]
            self._offset = 0
        return out[:limit]
