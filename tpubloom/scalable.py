"""Scalable (layered) bloom filter — grows when full, FPR stays bounded.

Parity: SURVEY.md §2.3 lists the scalable/layered filter as a capability of
the reference's Lua lineage (the README credits ErikDubbelboer's
redis-lua-scaling-bloom-filter scripts [PK]). The canonical design is the
scalable bloom filter of Almeida, Baquero, Preguiça & Hutchison (2007):

* a stack of plain bloom-filter *layers*; layer ``i`` holds
  ``capacity · growth^i`` keys at error rate ``error_rate · tightening^i``;
* inserts go to the newest layer; when it reaches capacity a fresh, larger,
  tighter layer is pushed;
* membership is the OR over layers, so the compound false-positive rate is
  bounded by ``sum_i p·r^i  <  error_rate / (1 - tightening)``.

TPU-first mechanics: each layer is an independent device-resident
:class:`~tpubloom.filter.BloomFilter` (packed uint32 array + jitted
scatter-OR/gather-AND kernels), deliberately *not* one fused array — layers
have different m and appear at data-dependent times, which would force
recompilation if baked into one kernel; a Python loop over a handful of
layers (layer count grows only logarithmically in total keys) keeps every
per-layer kernel static-shaped and cached. Each layer derives its own hash
seed so layer memberships are independent.

The growth policy lives in one class parameterized by a layer factory, so
the device filter and the CPU oracle (:class:`tpubloom.cpu_ref.CPUBloomFilter`)
share the exact same layering decisions — tests pin them against each other.
"""

from __future__ import annotations

from typing import Callable, Sequence

import numpy as np

from tpubloom.config import FilterConfig
from tpubloom.params import optimal_m_k, round_up_pow2


#: Seed derivation for layer i (any fixed odd constant; part of the filter's
#: identity like FilterConfig.seed itself): seed_i = (seed + i·LAYER_SEED_STRIDE) mod 2^32.
LAYER_SEED_STRIDE = 0x61C88647  # 2^32 / golden ratio, odd


def layer_config(
    base: FilterConfig,
    capacity: int,
    error_rate: float,
    layer: int,
    *,
    growth: int = 2,
    tightening: float = 0.5,
) -> tuple[FilterConfig, int]:
    """Config + capacity of layer ``layer`` under the scalable policy.

    Returns ``(config, layer_capacity)``. Deterministic in its inputs, so two
    implementations (device / CPU oracle) built with the same arguments
    produce interchangeable layer stacks.
    """
    cap_i = capacity * (growth ** layer)
    p_i = error_rate * (tightening ** layer)
    m, k = optimal_m_k(cap_i, p_i)
    m = round_up_pow2(m)
    if base.block_bits:
        # blocked layers need at least one whole block (more bits only
        # tightens the layer under its error budget)
        m = max(m, base.block_bits)
    seed_i = (base.seed + layer * LAYER_SEED_STRIDE) % (1 << 32)
    return base.replace(m=m, k=k, seed=seed_i, shards=1), cap_i


class _ScalableCore:
    """Layer-stack growth policy, shared by device and CPU variants."""

    def __init__(
        self,
        make_layer: Callable[[FilterConfig], object],
        config: FilterConfig,
        capacity: int,
        error_rate: float,
        *,
        growth: int = 2,
        tightening: float = 0.5,
    ):
        if config.counting:
            # layered delete is ill-defined (which layer holds the key?);
            # the counting variants are standalone filters, not layers
            raise ValueError(
                "scalable filters do not support counting configs — use "
                "CountingBloomFilter / BlockedCountingBloomFilter"
            )
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        if not (0.0 < error_rate < 1.0):
            raise ValueError(f"error_rate must be in (0, 1), got {error_rate}")
        if growth < 2:
            raise ValueError(f"growth must be >= 2, got {growth}")
        if not (0.0 < tightening < 1.0):
            raise ValueError(f"tightening must be in (0, 1), got {tightening}")
        self._make_layer = make_layer
        self.base_config = config
        self.capacity = capacity
        self.error_rate = error_rate
        self.growth = growth
        self.tightening = tightening
        self.layers: list = []
        self._layer_caps: list[int] = []
        self._layer_counts: list[int] = []
        self.n_inserted = 0
        self._push_layer()

    # -- growth -------------------------------------------------------------

    def _push_layer(self) -> None:
        cfg, cap = layer_config(
            self.base_config,
            self.capacity,
            self.error_rate,
            len(self.layers),
            growth=self.growth,
            tightening=self.tightening,
        )
        self.layers.append(self._make_layer(cfg))
        self._layer_caps.append(cap)
        self._layer_counts.append(0)

    def _room(self) -> int:
        return self._layer_caps[-1] - self._layer_counts[-1]

    # -- reference-parity API ----------------------------------------------

    def insert_batch(self, keys: Sequence[bytes | str]) -> None:
        """Insert, splitting across a growth boundary so every layer stays
        within its design capacity (the FPR bound depends on it)."""
        keys = list(keys)
        start = 0
        while start < len(keys):
            room = self._room()
            if room <= 0:
                self._push_layer()
                continue
            chunk = keys[start : start + room]
            self.layers[-1].insert_batch(chunk)
            self._layer_counts[-1] += len(chunk)
            self.n_inserted += len(chunk)
            start += len(chunk)

    def include_batch(self, keys: Sequence[bytes | str]) -> np.ndarray:
        """Membership = OR over layers (any layer claiming the key)."""
        out = np.zeros(len(keys), dtype=bool)
        for layer in self.layers:
            out |= np.asarray(layer.include_batch(keys))
        return out

    def insert(self, key: bytes | str) -> None:
        self.insert_batch([key])

    def include(self, key: bytes | str) -> bool:
        return bool(self.include_batch([key])[0])

    __contains__ = include

    def clear(self) -> None:
        self.layers = []
        self._layer_caps = []
        self._layer_counts = []
        self.n_inserted = 0
        self._push_layer()

    # -- persistence (layer-stack snapshot; tpubloom.checkpoint frames it) --

    def snapshot_meta(self) -> dict:
        """Everything needed to rebuild the layer stack except the payload
        bytes: the growth-policy parameters (they determine every layer's
        geometry) plus per-layer configs and fill counts. Captured under
        the caller's op lock so it is consistent with the layer words."""
        return {
            "capacity": self.capacity,
            "error_rate": self.error_rate,
            "growth": self.growth,
            "tightening": self.tightening,
            "layer_counts": list(self._layer_counts),
            "layer_configs": [layer.config.to_dict() for layer in self.layers],
        }

    def _load_layers(self, meta: dict, layer_words) -> None:
        """Replace the layer stack with a restored one (checkpoint restore).

        ``layer_words``: one np.uint32 array per layer, flattened payload
        order. Layer geometry is re-derived from the policy and verified
        against the stored configs — a checkpoint from a different policy
        or base config cannot be silently misread."""
        self.layers = []
        self._layer_caps = []
        self._layer_counts = []
        for i, (cfg_dict, count) in enumerate(
            zip(meta["layer_configs"], meta["layer_counts"])
        ):
            self._push_layer()
            got = self.layers[i].config.to_dict()
            # normalize the stored dict through from_dict so its legacy
            # shims apply (headers written before block_hash existed must
            # compare as the "ap" spec they were built with, exactly as
            # FilterConfig.from_dict restores them)
            cfg_dict = FilterConfig.from_dict(dict(cfg_dict)).to_dict()
            if got != cfg_dict:
                raise ValueError(
                    f"layer {i} config mismatch on restore: policy derives "
                    f"{got}, checkpoint holds {cfg_dict}"
                )
            self.layers[i]._set_words(layer_words[i])
            self._layer_counts[i] = int(count)
        self.n_inserted = sum(self._layer_counts)

    # -- observability ------------------------------------------------------

    @property
    def config(self):
        """The base/template config (key_name, layout, seed — NOT a layer's
        m/k). Lets config-keyed plumbing (server registry, checkpoint
        sinks) treat scalable filters uniformly."""
        return self.base_config

    @property
    def n_layers(self) -> int:
        return len(self.layers)

    def compound_fpr_bound(self) -> float:
        """Design-time upper bound on the compound FPR: sum of layer rates."""
        return sum(
            self.error_rate * self.tightening**i for i in range(len(self.layers))
        )

    def stats(self) -> dict:
        st = {
            "n_layers": self.n_layers,
            "n_inserted": self.n_inserted,
            "capacity_current_layer": self._layer_caps[-1],
            "count_current_layer": self._layer_counts[-1],
            "total_bits": sum(layer.config.m for layer in self.layers),
            "compound_fpr_bound": self.compound_fpr_bound(),
        }
        if all(hasattr(layer, "estimated_fpr") for layer in self.layers):
            # observed compound FPR (a query is a false positive when ANY
            # layer false-positives) vs the design bound = the scalable
            # variant's drift gauge
            miss = 1.0
            for layer in self.layers:
                miss *= 1.0 - layer.estimated_fpr()
            st["estimated_fpr"] = 1.0 - miss
            st["fpr_drift"] = st["estimated_fpr"] - st["compound_fpr_bound"]
            st["predicted_fpr"] = st["compound_fpr_bound"]
        return st


class ScalableBloomFilter(_ScalableCore):
    """Device-resident scalable filter: a stack of TPU filter layers.

    A base ``config`` with ``block_bits`` set builds BLOCKED layers —
    every layer then runs the blocked hot path (the Pallas sweep on TPU
    once a layer is large enough); flat configs keep the
    reference-compatible position spec per layer.
    """

    def __init__(
        self,
        capacity: int,
        error_rate: float,
        *,
        config: FilterConfig | None = None,
        growth: int = 2,
        tightening: float = 0.5,
    ):
        from tpubloom.filter import BlockedBloomFilter, BloomFilter

        base = config if config is not None else FilterConfig(m=64, k=1)
        factory = BlockedBloomFilter if base.block_bits else BloomFilter
        super().__init__(
            factory, base, capacity, error_rate,
            growth=growth, tightening=tightening,
        )

    def block_until_ready(self) -> None:
        for layer in self.layers:
            layer.block_until_ready()

    def include_batch(self, keys: Sequence[bytes | str]) -> np.ndarray:
        """Pack once, query every layer with the shared device arrays
        (layers share key_len/key_policy; only m/k/seed differ)."""
        keys = list(keys)
        keys_u8, lengths, B = self.layers[0]._pack_padded(keys)
        out = np.zeros(B, dtype=bool)
        for layer in self.layers:
            out |= np.asarray(layer.include_arrays(keys_u8, lengths))[:B]
        return out


class CPUScalableBloomFilter(_ScalableCore):
    """CPU-oracle scalable filter: same policy over CPUBloomFilter layers."""

    def __init__(
        self,
        capacity: int,
        error_rate: float,
        *,
        config: FilterConfig | None = None,
        growth: int = 2,
        tightening: float = 0.5,
        use_native: bool | None = None,
    ):
        from tpubloom.cpu_ref import CPUBlockedBloomFilter, CPUBloomFilter

        base = config if config is not None else FilterConfig(m=64, k=1)
        cpu_factory = CPUBlockedBloomFilter if base.block_bits else CPUBloomFilter

        def make_layer(cfg: FilterConfig):
            return cpu_factory(cfg, use_native=use_native)

        super().__init__(
            make_layer, base, capacity, error_rate,
            growth=growth, tightening=tightening,
        )
