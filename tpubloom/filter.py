"""BloomFilter / CountingBloomFilter — the framework's front-end classes.

Parity: mirrors the reference's public API — ``#insert`` / ``#include?`` /
``#clear`` on ``Redis::Bloomfilter`` (SURVEY.md §1 L1; BASELINE.json: "keeps
#insert / #include?") — plus the batch forms the north star adds
(``insert_batch`` / ``include_batch``), the counting variant (config 4), and
checkpoint import/export in the reference's Redis-string-bitmap format.

TPU-first mechanics:

* the bit array is a device-resident packed ``uint32`` array; insert/query
  are jit-compiled once per padded batch shape;
* the insert jit **donates** the bit-array buffer, so updates are in-place in
  HBM — no 512 MiB copy per batch at m=2^32;
* host batches are padded to the next power of two (min 64) to bound the
  jit cache; padded entries carry ``length = -1`` and are dropped in-kernel;
* ``insert_arrays`` / ``include_arrays`` accept pre-packed device arrays for
  zero-host-overhead streaming (bench path, gRPC server path).
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from tpubloom.config import FilterConfig
from tpubloom.obs import context as obs
from tpubloom.obs import counters as obs_counters
from tpubloom.ops import bitops, blocked, counting, hashing
from tpubloom.utils.packing import (
    pack_keys,
    redis_bitmap_to_words,
    words_to_redis_bitmap,
)


def _pad_to_bucket(n: int, minimum: int = 64) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


# -- pure kernels (shared with sharded/pipeline/graft paths) -----------------


def make_insert_fn(config: FilterConfig):
    """Pure ``(bits, keys_u8[B,L], lengths[B]) -> bits`` insert step.

    ``lengths < 0`` marks padding. This is the function the single-chip
    benchmark jits with buffer donation and the sharded filter wraps in
    ``shard_map``.
    """
    m, k, seed = config.m, config.k, config.seed

    def insert(bits, keys_u8, lengths):
        valid = lengths >= 0
        ph, pl = hashing.positions(
            keys_u8, jnp.maximum(lengths, 0), m=m, k=k, seed=seed
        )
        word, bit = hashing.split_word_bit(ph, pl)
        valid_k = jnp.broadcast_to(valid[..., None], word.shape)
        return bitops.scatter_or(bits, word.ravel(), bit.ravel(), valid_k.ravel())

    return insert


def make_query_fn(config: FilterConfig):
    """Pure ``(bits, keys_u8, lengths) -> bool[B]`` membership step."""
    m, k, seed = config.m, config.k, config.seed

    def query(bits, keys_u8, lengths):
        ph, pl = hashing.positions(
            keys_u8, jnp.maximum(lengths, 0), m=m, k=k, seed=seed
        )
        word, bit = hashing.split_word_bit(ph, pl)
        return bitops.query_membership(bits, word, bit)

    return query


def make_counter_fn(config: FilterConfig, *, increment: bool):
    m, k, seed = config.m, config.k, config.seed

    def update(words, keys_u8, lengths):
        valid = lengths >= 0
        ph, pl = hashing.positions(
            keys_u8, jnp.maximum(lengths, 0), m=m, k=k, seed=seed
        )
        del ph  # counting m < 2^31 => positions fit the low word
        pos = pl.astype(jnp.int32)
        valid_k = jnp.broadcast_to(valid[..., None], pos.shape)
        return counting.counter_update(
            words, pos.ravel(), valid_k.ravel(), increment=increment
        )

    return update


def make_counting_query_fn(config: FilterConfig):
    m, k, seed = config.m, config.k, config.seed

    def query(words, keys_u8, lengths):
        _, pl = hashing.positions(
            keys_u8, jnp.maximum(lengths, 0), m=m, k=k, seed=seed
        )
        return counting.counting_membership(words, pl.astype(jnp.int32))

    return query


def blocked_storage_fat(config: FilterConfig) -> bool:
    """Whether the persistent blocked storage uses the fat [NB/J, 128]
    view (the SAME row-major bytes as [NB, W]): XLA's tiled HBM layouts
    make narrow-lane arrays both slow to DMA and expensive to reshape,
    so every filter that can holds its device array fat. Applies to both
    plain-blocked and blocked-counting layouts (the fat counting sweep
    ships since round 4)."""
    w = config.words_per_block
    return 128 % w == 0 and config.n_blocks % (128 // w) == 0


def blocked_device_shape(config: FilterConfig) -> tuple[int, int]:
    """Device-array shape for blocked storage (plain or counting): the
    fat [NB*W/128, 128] view when :func:`blocked_storage_fat` holds,
    else the logical [NB, W]. The ONE place the fat geometry is spelled
    out for single-chip filters."""
    nb, w = config.n_blocks, config.words_per_block
    if blocked_storage_fat(config):
        return (nb * w // 128, 128)
    return (nb, w)


def make_blocked_insert_fn(config: FilterConfig, *, storage_fat: bool = False):
    """Pure ``(blocks[NB,W], keys_u8[B,L], lengths[B]) -> blocks`` insert for
    the blocked layout (ops.blocked spec).

    ``config.insert_path`` selects the implementation: the Pallas
    partition-sweep kernel (``tpubloom.ops.sweep`` — the TPU fast path)
    or the pure-XLA sorted scatter. Both produce bit-identical arrays;
    "auto" decides per (backend, batch shape) at trace time.
    ``storage_fat``: blocks are the fat [NB/J, 128] view in and out.
    """
    nb, bb, w = config.n_blocks, config.block_bits, config.words_per_block
    k, seed, bh = config.k, config.seed, config.block_hash

    def insert(blocks, keys_u8, lengths):
        from tpubloom.ops import sweep

        if sweep.resolve_insert_path(config, keys_u8.shape[0]) == "sweep":
            return sweep.make_sweep_insert_fn(config, storage_fat=storage_fat)(
                blocks, keys_u8, lengths
            )
        valid = lengths >= 0
        blk, bit = blocked.block_positions(
            keys_u8, jnp.maximum(lengths, 0),
            n_blocks=nb, block_bits=bb, k=k, seed=seed, block_hash=bh,
        )
        masks = blocked.build_masks(bit, w)
        if storage_fat:
            # scatter straight into the fat view (a [NB, W] <-> fat
            # reshape is a real copy on TPU; the lane fold is O(B))
            frow, m128 = blocked.fat_fold_masks(blk, masks, 128 // w)
            return blocked.blocked_insert(blocks, frow, m128, valid)
        return blocked.blocked_insert(blocks, blk, masks, valid)

    return insert


def make_blocked_counter_fn(
    config: FilterConfig, *, increment: bool, storage_fat: bool = False
):
    """Pure ``(blocks[NB,W], keys_u8, lengths) -> blocks`` update for the
    BLOCKED counting layout: all k 4-bit counters of a key live in one
    block (block_bits bits = block_bits/4 counters), so the sweep path
    touches one row per key instead of k scattered words.

    Position spec: ``blk`` as in ops.blocked; counter ``c_i = p_i mod
    counters_per_block``. The storage is bit-identical to the flat
    counting layout at positions ``blk * counters_per_block + c`` —
    which is exactly what the non-sweep fallback (and the CPU oracle)
    computes via ops.counting.counter_update on the raveled array.
    ``storage_fat``: blocks are the fat [NB/J, 128] view in and out
    (same raveled bytes, so the flat fallback is layout-agnostic).
    """
    nb, cpb, w = config.n_blocks, config.counters_per_block, config.words_per_block
    k, seed, bh = config.k, config.seed, config.block_hash

    def update(blocks, keys_u8, lengths):
        from tpubloom.ops import sweep

        if sweep.resolve_insert_path(config, keys_u8.shape[0]) == "sweep":
            if k > 15:
                # per-key multiplicity must fit the 4-bit stream nibbles
                if config.insert_path == "sweep":
                    raise ValueError(
                        "counting sweep supports k <= 15 — use "
                        "insert_path='scatter' (auto falls back silently)"
                    )
            else:
                return sweep.make_sweep_counter_fn(
                    config, increment=increment, storage_fat=storage_fat
                )(blocks, keys_u8, lengths)
        valid = lengths >= 0
        blk, cpos = blocked.block_positions(
            keys_u8, jnp.maximum(lengths, 0),
            n_blocks=nb, block_bits=cpb, k=k, seed=seed, block_hash=bh,
        )
        gpos = (blk[..., None] * cpb + cpos.astype(jnp.int32)).astype(jnp.int32)
        valid_k = jnp.broadcast_to(valid[..., None], gpos.shape)
        flat = counting.counter_update(
            blocks.reshape(-1), gpos.ravel(), valid_k.ravel(), increment=increment
        )
        return flat.reshape(blocks.shape)

    return update


def make_blocked_counting_query_fn(
    config: FilterConfig, *, storage_fat: bool = False
):
    """Pure ``(blocks, keys_u8, lengths) -> bool[B]`` blocked-counting
    membership: one row gather per key + all-counters-nonzero test.
    With ``storage_fat`` the gather reads fat [NB/J, 128] rows directly
    (row = blk // J, lane group blk % J), like the plain blocked query."""
    nb, cpb, w = config.n_blocks, config.counters_per_block, config.words_per_block
    k, seed, bh = config.k, config.seed, config.block_hash

    def query(blocks, keys_u8, lengths):
        blk, cpos = blocked.block_positions(
            keys_u8, jnp.maximum(lengths, 0),
            n_blocks=nb, block_bits=cpb, k=k, seed=seed, block_hash=bh,
        )
        if not storage_fat:
            return counting.blocked_counting_membership(blocks, blk, cpos)
        return counting.fat_blocked_counting_membership(blocks, blk, cpos, w)

    return query


def make_blocked_test_insert_fn(config: FilterConfig, *, storage_fat: bool = False):
    """Pure ``(blocks, keys_u8, lengths) -> (blocks, present[B])``
    test-and-insert for the blocked layout: ``present[i]`` is key i's
    membership BEFORE this batch (within-batch duplicates all report the
    pre-batch state; padded entries report False).

    Parity: the reference's Lua add script returns prior membership from
    the same server-side pass that sets the bits (SURVEY.md §2.1 ":lua"
    driver row); this is that fused hot path. On TPU the sweep kernel
    answers membership from the partition tile it is already updating —
    measurably faster than separate query + insert steps.
    """
    nb, bb, w = config.n_blocks, config.block_bits, config.words_per_block
    k, seed, bh = config.k, config.seed, config.block_hash

    def test_insert(blocks, keys_u8, lengths):
        from tpubloom.ops import sweep

        if (
            sweep.resolve_insert_path(config, keys_u8.shape[0], presence=True)
            == "sweep"
        ):
            return sweep.make_sweep_insert_fn(
                config, with_presence=True, storage_fat=storage_fat
            )(blocks, keys_u8, lengths)
        # scatter path: hash once, reuse positions for both the
        # membership test and the insert
        valid = lengths >= 0
        blk, bit = blocked.block_positions(
            keys_u8, jnp.maximum(lengths, 0),
            n_blocks=nb, block_bits=bb, k=k, seed=seed, block_hash=bh,
        )
        masks = blocked.build_masks(bit, w)
        if storage_fat:
            blk, masks = blocked.fat_fold_masks(blk, masks, 128 // w)
        present = blocked.blocked_query(blocks, blk, masks) & valid
        out = blocked.blocked_insert(blocks, blk, masks, valid)
        return out, present

    return test_insert


def make_blocked_query_fn(config: FilterConfig, *, storage_fat: bool = False):
    """Pure ``(blocks, keys_u8, lengths) -> bool[B]`` blocked membership.

    ``config.query_path`` selects the implementation (ISSUE 12): the
    read-only Pallas query sweep (``tpubloom.ops.sweep`` — sorted window
    fetch + nibble-extraction presence test, no write-back, no donated
    chain) or the row-gather XLA path. Both answer bit-identical
    verdicts; "auto" decides per (backend, batch shape) at trace time
    through :func:`tpubloom.ops.sweep.resolve_query_path`.

    With ``storage_fat`` the gather reads fat [NB/J, 128] rows directly
    (row = blk // J, lane group blk % J) — no reshape of the array."""
    nb, bb, w = config.n_blocks, config.block_bits, config.words_per_block
    k, seed, bh = config.k, config.seed, config.block_hash
    J = 128 // w if w and 128 % w == 0 else 1

    def query(blocks, keys_u8, lengths):
        from tpubloom.ops import sweep

        # effective (not just resolved) path: a forced "sweep" on a
        # shape the kernel cannot take demotes to the gather here —
        # served filters see arbitrary batch sizes
        if sweep.effective_query_path(config, keys_u8.shape[0]) == "sweep":
            return sweep.make_sweep_query_fn(config, storage_fat=storage_fat)(
                blocks, keys_u8, lengths
            )
        blk, bit = blocked.block_positions(
            keys_u8, jnp.maximum(lengths, 0),
            n_blocks=nb, block_bits=bb, k=k, seed=seed, block_hash=bh,
        )
        masks = blocked.build_masks(bit, w)
        if not storage_fat:
            return blocked.blocked_query(blocks, blk, masks)
        return blocked.fat_blocked_query(blocks, blk, masks)

    return query


# -- front-end classes -------------------------------------------------------


class _FilterBase:
    """Shared packing / padding / batch plumbing.

    Subclasses provide ``self._insert`` / ``self._query`` (jitted pure
    kernels over ``self.words``) and inherit the whole batch + scalar API;
    only construction, stats, and persistence differ per variant.
    """

    def __init__(self, config: FilterConfig, n_storage_words: int):
        self.config = config
        self.n_inserted = 0
        self.n_queried = 0
        self.words = jnp.zeros((n_storage_words,), jnp.uint32)

    def _pack_padded(self, keys: Sequence[bytes | str]):
        # obs.phase spans are no-ops outside an active request context
        # (the gRPC server / bench open one) — see tpubloom.obs.context
        with obs.phase("host_prep"):
            keys_u8, lengths = pack_keys(
                keys, self.config.key_len, key_policy=self.config.key_policy
            )
            B = len(keys)
            Bp = _pad_to_bucket(B)
            if Bp != B:
                keys_u8 = np.pad(keys_u8, ((0, Bp - B), (0, 0)))
                lengths = np.pad(lengths, (0, Bp - B), constant_values=-1)
        return keys_u8, lengths, B

    def _stage_batch(self, keys_u8, lengths):
        """H2D staging under its own phase span, so the breakdown
        separates transfer-bound from kernel-bound time server-side."""
        with obs.phase("h2d"):
            return jnp.asarray(keys_u8), jnp.asarray(lengths)

    def _prep_packed(self, rows: np.ndarray):
        """Host prep for FIXED-WIDTH pre-packed keys (the ``fixed`` wire
        encoding, ISSUE 10): ``rows`` is ``uint8[B, W]`` — every key
        exactly W bytes. Skips the per-key packing loop entirely; pads
        columns to ``key_len`` and rows to the jit bucket (both
        vectorized; zero copies when W == key_len and B is already a
        bucket size)."""
        with obs.phase("host_prep"):
            B, W = rows.shape
            key_len = self.config.key_len
            if W > key_len:
                raise ValueError(
                    f"fixed-width keys are {W} bytes > key_len={key_len}; "
                    "ship them msgpack-encoded (key_policy applies there)"
                )
            if W < key_len:
                rows = np.pad(rows, ((0, 0), (0, key_len - W)))
            lengths = np.full((B,), W, dtype=np.int32)
            Bp = _pad_to_bucket(B)
            if Bp != B:
                rows = np.pad(rows, ((0, Bp - B), (0, 0)))
                lengths = np.pad(lengths, (0, Bp - B), constant_values=-1)
        return rows, lengths, B

    # staged pipeline API (ISSUE 10): host_prep + H2D split from the
    # kernel launch, so a batching caller (the server's ingestion
    # coalescer, bench drivers) can stage batch N+1 while batch N's
    # kernel is still in flight, then fence N via the returned handle —
    # double-buffering the host feed against the device.

    def stage_batch(self, keys=None, *, rows=None):
        """Host prep + H2D only — returns an opaque staged batch for
        :meth:`launch_insert` / :meth:`launch_query`. Exactly one of
        ``keys`` (a key sequence) or ``rows`` (fixed-width ``uint8[B,
        W]``) must be given."""
        if rows is not None:
            keys_u8, lengths, B = self._prep_packed(np.asarray(rows, np.uint8))
        else:
            keys_u8, lengths, B = self._pack_padded(keys)
        d_keys, d_lengths = self._stage_batch(keys_u8, lengths)
        return d_keys, d_lengths, B

    def launch_insert(self, staged):
        """Launch the insert kernel on a staged batch WITHOUT the
        completion fence; returns the output array handle the caller
        fences on (``.block_until_ready()``) before acking the batch."""
        d_keys, d_lengths, B = staged
        with obs.phase("kernel"):
            self.words = self._insert(self.words, d_keys, d_lengths)
        self.n_inserted += B
        return self.words

    def launch_query(self, staged):
        """Launch the membership kernel on a staged batch; returns
        ``(device hits, valid count)`` — the caller's ``np.asarray`` is
        the fence + D2H. Query device work runs under its own
        ``kernel_query`` phase (ISSUE 12) so the read path's device
        time is separable from the write path's in every dashboard."""
        d_keys, d_lengths, B = staged
        self._query_launch_counter(d_keys.shape[0])
        with obs.phase("kernel_query"):
            hits = self._query(self.words, d_keys, d_lengths)
        self.n_queried += B
        return hits, B

    def _kernel_fence(self, handle) -> None:
        """Completion fence for one launched kernel (under an active
        request context). ShardedBloomFilter overrides it to record
        per-shard device-completion phases (ROADMAP 1(c))."""
        handle.block_until_ready()

    def _query_launch_counter(self, padded_batch: int) -> None:
        """Launch-mix hook (ISSUE 12): BlockedBloomFilter counts which
        membership path each query launch resolves to. No-op for
        layouts without a query-path split."""

    # fixed-width batch API (the `fixed` wire encoding's server path)

    def insert_packed(self, rows: np.ndarray) -> int:
        """Insert fixed-width pre-packed keys (``uint8[B, W]``, W <=
        key_len) — the zero-copy decode path of the ``fixed`` wire
        encoding."""
        out = self.launch_insert(self.stage_batch(rows=rows))
        if obs.current() is not None:
            # same honesty fence as insert_batch: under an active
            # request the kernel phase must cover real device work
            with obs.phase("kernel"):
                self._kernel_fence(out)
        return int(rows.shape[0])

    def include_packed(self, rows: np.ndarray) -> np.ndarray:
        """Membership for fixed-width pre-packed keys."""
        hits, B = self.launch_query(self.stage_batch(rows=rows))
        if obs.current() is not None:
            with obs.phase("kernel_query"):
                self._kernel_fence(hits)
        with obs.phase("d2h"):
            out = np.asarray(hits)
        return out[:B]

    def block_until_ready(self) -> None:
        self.words.block_until_ready()

    @property
    def words_logical(self) -> np.ndarray:
        """Host copy of the storage in its LOGICAL shape — what oracles,
        tools, and tests should compare against. For flat filters this is
        the device shape; :class:`BlockedBloomFilter` overrides it to
        undo the fat [NB/J, 128] device view (same row-major bytes)."""
        return np.asarray(self.words)

    def _set_words(self, words) -> None:
        """Replace storage from a flat array (checkpoint restore)."""
        self.words = jnp.asarray(
            np.asarray(words, dtype=np.uint32).reshape(self.words.shape)
        )

    def clear(self) -> None:
        """Reference ``#clear`` — zero the array (SURVEY.md §3.4: DEL becomes
        ``jnp.zeros_like``)."""
        self.words = jnp.zeros_like(self.words)
        self.n_inserted = 0

    # batch API (the north-star surface)

    def insert_batch(self, keys: Sequence[bytes | str]) -> None:
        keys_u8, lengths, B = self._pack_padded(keys)
        keys_u8, lengths = self._stage_batch(keys_u8, lengths)
        with obs.phase("kernel"):
            self.words = self._insert(self.words, keys_u8, lengths)
            if obs.current() is not None:
                # fence so the kernel phase covers real device work, not
                # just async dispatch; only under an active request (the
                # library/streaming path keeps JAX's async pipelining).
                # Cost on the server path is negligible: the per-filter
                # op lock + donation data dependence already serialize
                # same-filter work, and the gRPC hop is transport-bound
                # at ~1/50 of device rate (benchmarks grpc_path_r5)
                self._kernel_fence(self.words)
        self.n_inserted += B

    def include_batch(self, keys: Sequence[bytes | str]) -> np.ndarray:
        keys_u8, lengths, B = self._pack_padded(keys)
        keys_u8, lengths = self._stage_batch(keys_u8, lengths)
        self._query_launch_counter(keys_u8.shape[0])
        with obs.phase("kernel_query"):
            hits = self._query(self.words, keys_u8, lengths)
            if obs.current() is not None:
                self._kernel_fence(hits)
        with obs.phase("d2h"):
            out = np.asarray(hits)
        self.n_queried += B
        return out[:B]

    # pre-packed device-array API (bench / server / streaming path)

    def insert_arrays(self, keys_u8, lengths, *, n_valid: int | None = None) -> None:
        """``n_valid`` = true key count when the batch carries static-shape
        padding (lengths = -1 rows set no bits but must not inflate
        ``n_inserted`` — it is persisted into checkpoints)."""
        self.words = self._insert(self.words, keys_u8, lengths)
        self.n_inserted += int(keys_u8.shape[0]) if n_valid is None else n_valid

    def include_arrays(self, keys_u8, lengths):
        self.n_queried += int(keys_u8.shape[0])
        return self._query(self.words, keys_u8, lengths)

    # scalar API (reference parity)

    def insert(self, key: bytes | str) -> None:
        self.insert_batch([key])

    def include(self, key: bytes | str) -> bool:
        return bool(self.include_batch([key])[0])

    __contains__ = include

    # observability (SURVEY.md §5 metrics: fill ratio & predicted FPR;
    # the /metrics gauges in tpubloom.obs.exposition read these)

    def fill_ratio(self) -> float:
        if self.config.counting:
            raise ValueError("fill_ratio is for plain/blocked filters")
        return float(bitops.popcount_fill(self.words, self.config.m))

    def estimated_fpr(self) -> float:
        return self.fill_ratio() ** self.config.k

    def predicted_fpr(self) -> float:
        """Analytic FPR from the geometry and ``n_inserted`` alone:
        ``(1 - e^{-kn/m})^k``. Contrast with :meth:`estimated_fpr`
        (computed from the OBSERVED fill) — the gap between them is the
        ``fpr_drift`` gauge: sustained drift means the deployed key
        distribution (duplicates, adversarial keys) or a kernel
        regression is violating the sizing model the filter was
        provisioned with."""
        m, k = self.config.m, self.config.k
        if self.config.block_bits:
            # the blocked layout's own (measurement-pinned) model — using
            # the flat formula here would misread the layout's inherent
            # FPR excess at high fill as deployment drift
            from tpubloom.params import blocked_fpr

            return blocked_fpr(
                self.n_inserted,
                m=m,
                k=k,
                block_bits=self.config.block_bits,
                block_hash=self.config.block_hash,
            )
        return (1.0 - math.exp(-k * self.n_inserted / m)) ** k

    def _fpr_gauges(self) -> dict:
        """fill/bits/FPR gauge block shared by the non-counting stats()."""
        fill = self.fill_ratio()
        estimated = fill**self.config.k
        predicted = self.predicted_fpr()
        return {
            "fill_ratio": fill,
            "bits_set": int(round(fill * self.config.m)),
            "estimated_fpr": estimated,
            "predicted_fpr": predicted,
            "fpr_drift": estimated - predicted,
        }


class BloomFilter(_FilterBase):
    """Plain bloom filter on a packed ``uint32`` device array."""

    def __init__(self, config: FilterConfig):
        if config.counting:
            raise ValueError("use CountingBloomFilter for counting configs")
        super().__init__(config, config.n_words)
        self._insert = jax.jit(make_insert_fn(config), donate_argnums=0)
        self._query = jax.jit(make_query_fn(config))

    def stats(self) -> dict:
        return {
            "m": self.config.m,
            "k": self.config.k,
            "n_inserted": self.n_inserted,
            "n_queried": self.n_queried,
            **self._fpr_gauges(),
        }

    # persistence (Redis-string-bitmap format, reference-compatible)

    def to_redis_bitmap(self) -> bytes:
        return words_to_redis_bitmap(np.asarray(self.words), self.config.m)

    @classmethod
    def from_redis_bitmap(cls, config: FilterConfig, data: bytes) -> "BloomFilter":
        f = cls(config)
        f.words = jnp.asarray(redis_bitmap_to_words(data, config.m))
        return f


class BlockedBloomFilter(_FilterBase):
    """Blocked (cache-line) bloom filter — the throughput layout.

    All k bits of a key live in one ``config.block_bits``-sized block, so
    every op touches one contiguous row instead of k scattered words —
    ~k× less random HBM traffic than :class:`BloomFilter` (see
    tpubloom.ops.blocked for the measured rationale and the exact spec).
    Use when raw insert/query rate matters more than the last ~fraction of
    FPR headroom at high fill; not bit-compatible with the flat layout.

    Storage layout: ``self.words`` is the DEVICE array and, whenever
    ``blocked_storage_fat(config)`` holds, uses the fat ``[NB/J, 128]``
    view (J = 128 // words_per_block) — the SAME row-major bytes as the
    logical ``[n_blocks, words_per_block]`` array, folded J blocks per
    row so DMA runs at full 128-lane width (benchmarks/RESULTS_r3.md §2
    measured 5× on this). Read ``words_logical`` for the logical shape;
    ``to_bytes``/``from_bytes`` are layout-agnostic (row-major bytes are
    identical under both views).
    """

    def __init__(self, config: FilterConfig):
        if config.counting:
            # a counting config reinterprets m as counters (4 bits each);
            # building a plain blocked filter from it would silently use
            # the wrong geometry and drop delete support
            raise ValueError(
                "use BlockedCountingBloomFilter for counting configs"
            )
        if not config.block_bits:
            config = config.replace(block_bits=512)
        super().__init__(config, 0)  # placeholder; storage is 2-D
        # fat [NB/J, 128] storage where possible: the SAME row-major
        # bytes as [NB, W], but XLA's tiled HBM layouts DMA narrow-lane
        # arrays at ~1/5 speed and make the reshape a real copy
        # (benchmarks/RESULTS_r3.md) — so the persistent array stays fat
        # and every kernel/gather reads it natively
        self._fat = blocked_storage_fat(config)
        self.words = jnp.zeros(blocked_device_shape(config), jnp.uint32)
        self._insert = jax.jit(
            make_blocked_insert_fn(config, storage_fat=self._fat),
            donate_argnums=0,
        )
        self._query = jax.jit(
            make_blocked_query_fn(config, storage_fat=self._fat)
        )
        self._test_insert = None  # jitted lazily on first return_presence use

    def insert_batch(
        self, keys: Sequence[bytes | str], *, return_presence: bool = False
    ):
        """Insert a batch; with ``return_presence`` also report each key's
        membership BEFORE the batch (test-and-insert, one fused device
        pass on the sweep path — the reference Lua add script's
        semantics). Within-batch duplicates all report the pre-batch
        state."""
        if not return_presence:
            return super().insert_batch(keys)
        if self._test_insert is None:
            self._test_insert = jax.jit(
                make_blocked_test_insert_fn(
                    self.config, storage_fat=self._fat
                ),
                donate_argnums=0,
            )
        keys_u8, lengths, B = self._pack_padded(keys)
        keys_u8, lengths = self._stage_batch(keys_u8, lengths)
        with obs.phase("kernel"):
            self.words, present = self._test_insert(self.words, keys_u8, lengths)
            if obs.current() is not None:
                self._kernel_fence(present)
        with obs.phase("d2h"):
            out = np.asarray(present)
        self.n_inserted += B
        return out[:B]

    def _query_launch_counter(self, padded_batch: int) -> None:
        """Launch-mix counters (ISSUE 12): which membership path this
        launch resolves to — the same deterministic funnel the traced
        kernel used (``resolve_query_path`` is pure in (config, backend,
        padded batch shape)), counted host-side because the decision is
        made at trace time and invisible to per-launch instrumentation.
        ``query_sweep_launches`` + ``query_gather_launches`` sum to all
        blocked query launches; a nonzero gather count on a TPU host
        says batches are falling off the query kernel's envelope."""
        from tpubloom.ops import sweep

        if sweep.effective_query_path(self.config, max(1, padded_batch)) == "sweep":
            obs_counters.incr("query_sweep_launches")
        else:
            obs_counters.incr("query_gather_launches")

    @property
    def words_logical(self) -> np.ndarray:
        return np.asarray(self.words).reshape(
            self.config.n_blocks, self.config.words_per_block
        )

    def stats(self) -> dict:
        return {
            "m": self.config.m,
            "k": self.config.k,
            "block_bits": self.config.block_bits,
            "n_inserted": self.n_inserted,
            "n_queried": self.n_queried,
            **self._fpr_gauges(),
        }

    # persistence (raw little-endian words, row-major; NOT the Redis bitmap
    # format — blocked arrays are a different position spec)

    def to_bytes(self) -> bytes:
        return np.asarray(self.words).astype("<u4").tobytes()

    @classmethod
    def from_bytes(cls, config: FilterConfig, data: bytes) -> "BlockedBloomFilter":
        f = cls(config)
        arr = np.frombuffer(data, dtype="<u4").astype(np.uint32)
        f.words = jnp.asarray(arr.reshape(f.words.shape))
        return f


class BlockedCountingBloomFilter(_FilterBase):
    """Blocked (cache-line) counting filter — delete support at the
    blocked layout's throughput.

    All k 4-bit counters of a key live in one ``block_bits``-bit block
    (``block_bits/4`` counters), so updates/queries touch one contiguous
    row instead of k scattered words; on TPU the insert/delete hot loop
    runs as the Pallas counting sweep (``tpubloom.ops.sweep``). ``m``
    counts COUNTERS, as in :class:`CountingBloomFilter`. Same saturation
    semantics (increments clamp at 15, decrements floor at 0, one clamp
    per batch against the pre-batch value).
    """

    def __init__(self, config: FilterConfig):
        if not config.counting:
            config = config.replace(counting=True)
        if not config.block_bits:
            config = config.replace(block_bits=512)
        if config.m >= (1 << 31):
            raise ValueError("counting filters support m < 2^31")
        super().__init__(config, 0)  # storage is 2-D
        # fat [NB/J, 128] storage where possible, like BlockedBloomFilter
        # (same row-major bytes as [NB, W]; 128-lane DMA tier)
        self._fat = blocked_storage_fat(config)
        self.words = jnp.zeros(blocked_device_shape(config), jnp.uint32)
        self._insert = jax.jit(
            make_blocked_counter_fn(
                config, increment=True, storage_fat=self._fat
            ),
            donate_argnums=0,
        )
        self._delete = jax.jit(
            make_blocked_counter_fn(
                config, increment=False, storage_fat=self._fat
            ),
            donate_argnums=0,
        )
        self._query = jax.jit(
            make_blocked_counting_query_fn(config, storage_fat=self._fat)
        )

    @property
    def words_logical(self) -> np.ndarray:
        return np.asarray(self.words).reshape(
            self.config.n_blocks, self.config.words_per_block
        )

    def delete_batch(self, keys: Sequence[bytes | str]) -> None:
        keys_u8, lengths, B = self._pack_padded(keys)
        self.words = self._delete(self.words, keys_u8, lengths)
        self.n_inserted = max(0, self.n_inserted - B)

    def delete(self, key: bytes | str) -> None:
        self.delete_batch([key])

    def stats(self) -> dict:
        return {
            "m": self.config.m,
            "k": self.config.k,
            "block_bits": self.config.block_bits,
            "n_inserted": self.n_inserted,
            "n_queried": self.n_queried,
        }

    def to_bytes(self) -> bytes:
        return np.asarray(self.words).astype("<u4").tobytes()

    @classmethod
    def from_bytes(
        cls, config: FilterConfig, data: bytes
    ) -> "BlockedCountingBloomFilter":
        f = cls(config)
        arr = np.frombuffer(data, dtype="<u4").astype(np.uint32)
        f.words = jnp.asarray(arr.reshape(f.words.shape))
        return f


class CountingBloomFilter(_FilterBase):
    """Counting bloom filter: 4-bit saturating counters, supports delete."""

    def __init__(self, config: FilterConfig):
        if not config.counting:
            config = config.replace(counting=True)
        if config.m >= (1 << 31):
            raise ValueError("counting filters support m < 2^31 (config 4: m=2^30)")
        super().__init__(config, config.n_counter_words)
        self._insert = jax.jit(make_counter_fn(config, increment=True), donate_argnums=0)
        self._delete = jax.jit(make_counter_fn(config, increment=False), donate_argnums=0)
        self._query = jax.jit(make_counting_query_fn(config))

    def delete_batch(self, keys: Sequence[bytes | str]) -> None:
        keys_u8, lengths, B = self._pack_padded(keys)
        self.words = self._delete(self.words, keys_u8, lengths)
        self.n_inserted = max(0, self.n_inserted - B)

    def delete(self, key: bytes | str) -> None:
        self.delete_batch([key])

    def to_bytes(self) -> bytes:
        return np.asarray(self.words).astype("<u4").tobytes()

    @classmethod
    def from_bytes(cls, config: FilterConfig, data: bytes) -> "CountingBloomFilter":
        f = cls(config)
        f.words = jnp.asarray(np.frombuffer(data, dtype="<u4").astype(np.uint32))
        return f
