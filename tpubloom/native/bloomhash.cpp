// bloomhash — native (C++) host-side hot path for tpubloom.
//
// Parity role: the reference's only native component is the Redis C server
// (storage + server-side execution; SURVEY.md §2.1 "Native-component
// obligation"). In this framework the accelerated tier is XLA:TPU; this
// library is the *host* native tier: bit-exact MurmurHash3_x86_32 / FNV-1a,
// double-hash position derivation, and packed bit-array insert/query loops
// used by the CPU oracle (BASELINE config 1) and by the gRPC server for
// fast key packing. Must match tpubloom/ops/hashing.py bit for bit — tests
// enforce parity against the jnp and NumPy implementations.
//
// Built as a shared library via g++ (no Rust in the environment); loaded
// through ctypes (no pybind11 in the environment).

#include <cstdint>
#include <cstring>

static inline uint32_t rotl32(uint32_t x, int r) {
  return (x << r) | (x >> (32 - r));
}

static inline uint32_t fmix32(uint32_t h) {
  h ^= h >> 16;
  h *= 0x85ebca6bu;
  h ^= h >> 13;
  h *= 0xc2b2ae35u;
  h ^= h >> 16;
  return h;
}

// MurmurHash3_x86_32 (public-domain algorithm by Austin Appleby).
static uint32_t murmur3_32(const uint8_t* data, int len, uint32_t seed) {
  const uint32_t c1 = 0xcc9e2d51u, c2 = 0x1b873593u;
  uint32_t h1 = seed;
  const int nblocks = len / 4;
  for (int i = 0; i < nblocks; i++) {
    uint32_t k1;
    std::memcpy(&k1, data + 4 * i, 4);  // little-endian load
    k1 *= c1;
    k1 = rotl32(k1, 15);
    k1 *= c2;
    h1 ^= k1;
    h1 = rotl32(h1, 13);
    h1 = h1 * 5u + 0xe6546b64u;
  }
  const uint8_t* tail = data + 4 * nblocks;
  uint32_t k1 = 0;
  switch (len & 3) {
    case 3: k1 ^= (uint32_t)tail[2] << 16; [[fallthrough]];
    case 2: k1 ^= (uint32_t)tail[1] << 8;  [[fallthrough]];
    case 1:
      k1 ^= tail[0];
      k1 *= c1;
      k1 = rotl32(k1, 15);
      k1 *= c2;
      h1 ^= k1;
  }
  h1 ^= (uint32_t)len;
  return fmix32(h1);
}

static uint32_t fnv1a_32(const uint8_t* data, int len) {
  uint32_t h = 0x811c9dc5u;
  for (int i = 0; i < len; i++) {
    h ^= data[i];
    h *= 0x01000193u;
  }
  return h;
}

// Seed-derivation constants — must match tpubloom/ops/hashing.py.
static const uint32_t SEED_XOR_HB = 0x9E3779B9u;
static const uint32_t SEED_XOR_GB = 0x85EBCA6Bu;

extern "C" {

void bh_murmur3_batch(const uint8_t* keys, const int32_t* lens, int64_t B,
                      int32_t L, uint32_t seed, uint32_t* out) {
  for (int64_t i = 0; i < B; i++) {
    out[i] = murmur3_32(keys + i * L, lens[i], seed);
  }
}

void bh_fnv1a_batch(const uint8_t* keys, const int32_t* lens, int64_t B,
                    int32_t L, uint32_t* out) {
  for (int64_t i = 0; i < B; i++) {
    out[i] = fnv1a_32(keys + i * L, lens[i]);
  }
}

// k positions per key, exact spec of tpubloom/ops/hashing.py:
//   pow2 m:      pos_i = (H1 + i*H2 mod 2^64) mod m
//   non-pow2 m:  pos_i = ((h_a + i*(g_a|1)) mod 2^32) mod m
void bh_positions(const uint8_t* keys, const int32_t* lens, int64_t B,
                  int32_t L, uint64_t m, int32_t k, uint32_t seed,
                  uint64_t* out) {
  const bool pow2 = (m & (m - 1)) == 0;
  for (int64_t i = 0; i < B; i++) {
    const uint8_t* key = keys + i * L;
    const int len = lens[i];
    const uint32_t h_a = murmur3_32(key, len, seed);
    if (pow2) {
      const uint32_t h_b = murmur3_32(key, len, seed ^ SEED_XOR_HB);
      const uint32_t g_a = fnv1a_32(key, len);
      const uint32_t g_b = murmur3_32(key, len, seed ^ SEED_XOR_GB);
      const uint64_t H1 = ((uint64_t)h_b << 32) | h_a;
      const uint64_t H2 = (((uint64_t)g_b << 32) | g_a) | 1ull;
      uint64_t pos = H1;
      for (int j = 0; j < k; j++) {
        out[i * k + j] = pos & (m - 1);
        pos += H2;  // u64 wrap == mod 2^64
      }
    } else {
      const uint32_t g_a = fnv1a_32(key, len) | 1u;
      uint32_t pos = h_a;
      for (int j = 0; j < k; j++) {
        out[i * k + j] = pos % (uint32_t)m;
        pos += g_a;  // u32 wrap == mod 2^32
      }
    }
  }
}

// Packed-u32 bit-array ops (LSB-first within word, same layout as device).
void bh_insert(uint32_t* words, const uint64_t* pos, int64_t n) {
  for (int64_t i = 0; i < n; i++) {
    words[pos[i] >> 5] |= 1u << (pos[i] & 31);
  }
}

void bh_query(const uint32_t* words, const uint64_t* pos, int64_t B,
              int32_t k, uint8_t* out) {
  for (int64_t i = 0; i < B; i++) {
    uint8_t hit = 1;
    for (int32_t j = 0; j < k; j++) {
      const uint64_t p = pos[i * k + j];
      hit &= (uint8_t)((words[p >> 5] >> (p & 31)) & 1u);
      if (!hit) break;  // short-circuit, like the reference's :ruby driver
    }
    out[i] = hit;
  }
}

// Fused hash+insert / hash+query — the native CPU baseline hot loop
// (BASELINE config 1 measures this tier).
void bh_hash_insert(uint32_t* words, const uint8_t* keys, const int32_t* lens,
                    int64_t B, int32_t L, uint64_t m, int32_t k,
                    uint32_t seed) {
  const bool pow2 = (m & (m - 1)) == 0;
  for (int64_t i = 0; i < B; i++) {
    const uint8_t* key = keys + i * L;
    const int len = lens[i];
    const uint32_t h_a = murmur3_32(key, len, seed);
    if (pow2) {
      const uint32_t h_b = murmur3_32(key, len, seed ^ SEED_XOR_HB);
      const uint32_t g_a = fnv1a_32(key, len);
      const uint32_t g_b = murmur3_32(key, len, seed ^ SEED_XOR_GB);
      const uint64_t H2 = (((uint64_t)g_b << 32) | g_a) | 1ull;
      uint64_t pos = ((uint64_t)h_b << 32) | h_a;
      for (int j = 0; j < k; j++) {
        const uint64_t p = pos & (m - 1);
        words[p >> 5] |= 1u << (p & 31);
        pos += H2;
      }
    } else {
      const uint32_t g_a = fnv1a_32(key, len) | 1u;
      uint32_t pos = h_a;
      for (int j = 0; j < k; j++) {
        const uint32_t p = pos % (uint32_t)m;
        words[p >> 5] |= 1u << (p & 31);
        pos += g_a;
      }
    }
  }
}

void bh_hash_query(const uint32_t* words, const uint8_t* keys,
                   const int32_t* lens, int64_t B, int32_t L, uint64_t m,
                   int32_t k, uint32_t seed, uint8_t* out) {
  const bool pow2 = (m & (m - 1)) == 0;
  for (int64_t i = 0; i < B; i++) {
    const uint8_t* key = keys + i * L;
    const int len = lens[i];
    const uint32_t h_a = murmur3_32(key, len, seed);
    uint8_t hit = 1;
    if (pow2) {
      const uint32_t h_b = murmur3_32(key, len, seed ^ SEED_XOR_HB);
      const uint32_t g_a = fnv1a_32(key, len);
      const uint32_t g_b = murmur3_32(key, len, seed ^ SEED_XOR_GB);
      const uint64_t H2 = (((uint64_t)g_b << 32) | g_a) | 1ull;
      uint64_t pos = ((uint64_t)h_b << 32) | h_a;
      for (int j = 0; j < k && hit; j++) {
        const uint64_t p = pos & (m - 1);
        hit &= (uint8_t)((words[p >> 5] >> (p & 31)) & 1u);
        pos += H2;
      }
    } else {
      const uint32_t g_a = fnv1a_32(key, len) | 1u;
      uint32_t pos = h_a;
      for (int j = 0; j < k && hit; j++) {
        const uint32_t p = pos % (uint32_t)m;
        hit &= (uint8_t)((words[p >> 5] >> (p & 31)) & 1u);
        pos += g_a;
      }
    }
    out[i] = hit;
  }
}

// Blocked (cache-line) layout — fused hash+insert / hash+query, exact spec
// of tpubloom/ops/blocked.py: blk = h_a mod n_blocks. In-block positions per
// the `chunk` flag: chunk=1 slices log2(block_bits)-bit chunks from the
// (h_b, g_a, g_b) 96-bit pool; chunk=0 is the legacy AP walk
// (g_a + i*(g_b|1)) mod block_bits. words is uint32[n_blocks * W] row-major,
// W = block_bits/32.
static inline void blocked_positions_one(const uint8_t* key, int len,
                                         uint32_t seed, int32_t block_bits,
                                         int32_t k, int32_t chunk,
                                         uint32_t* bits) {
  const uint32_t bmask = (uint32_t)block_bits - 1u;
  const uint32_t g_a = fnv1a_32(key, len);
  const uint32_t g_b = murmur3_32(key, len, seed ^ SEED_XOR_GB);
  if (chunk) {
    int nb = 0;
    while ((1 << nb) < block_bits) nb++;
    const uint32_t pool[3] = {murmur3_32(key, len, seed ^ SEED_XOR_HB), g_a,
                              g_b};
    for (int j = 0; j < k; j++) {
      const int sh = j * nb;
      const int w = sh >> 5, off = sh & 31;
      uint32_t v = pool[w] >> off;
      if (off + nb > 32) v |= pool[w + 1] << (32 - off);
      bits[j] = v & bmask;
    }
  } else {
    const uint32_t stride = g_b | 1u;
    uint32_t p = g_a;
    for (int j = 0; j < k; j++) {
      bits[j] = p & bmask;
      p += stride;  // u32 wrap == mod 2^32
    }
  }
}

void bh_blocked_insert(uint32_t* words, const uint8_t* keys,
                       const int32_t* lens, int64_t B, int32_t L,
                       uint64_t n_blocks, int32_t block_bits, int32_t k,
                       uint32_t seed, int32_t chunk) {
  const int64_t W = block_bits / 32;
  uint32_t bits[64];
  for (int64_t i = 0; i < B; i++) {
    const uint8_t* key = keys + i * L;
    const int len = lens[i];
    const uint32_t h_a = murmur3_32(key, len, seed);
    blocked_positions_one(key, len, seed, block_bits, k, chunk, bits);
    uint32_t* row = words + (uint64_t)(h_a & (uint32_t)(n_blocks - 1)) * W;
    for (int j = 0; j < k; j++) row[bits[j] >> 5] |= 1u << (bits[j] & 31);
  }
}

void bh_blocked_query(const uint32_t* words, const uint8_t* keys,
                      const int32_t* lens, int64_t B, int32_t L,
                      uint64_t n_blocks, int32_t block_bits, int32_t k,
                      uint32_t seed, int32_t chunk, uint8_t* out) {
  const int64_t W = block_bits / 32;
  uint32_t bits[64];
  for (int64_t i = 0; i < B; i++) {
    const uint8_t* key = keys + i * L;
    const int len = lens[i];
    const uint32_t h_a = murmur3_32(key, len, seed);
    blocked_positions_one(key, len, seed, block_bits, k, chunk, bits);
    const uint32_t* row = words + (uint64_t)(h_a & (uint32_t)(n_blocks - 1)) * W;
    uint8_t hit = 1;
    for (int j = 0; j < k && hit; j++)
      hit &= (uint8_t)((row[bits[j] >> 5] >> (bits[j] & 31)) & 1u);
    out[i] = hit;
  }
}

// Host key packing: scatter a concatenated key buffer into the padded
// uint8[B, L] matrix the device kernels consume (out must be pre-zeroed).
// This is the framework's C++ ingest hot loop (SURVEY.md §7: "hash on
// host in C++ and ship only ... per key" — here we ship packed bytes);
// the pure-Python per-key loop in utils/packing.py is ~10x slower.
void bh_pack(const uint8_t* joined, const int32_t* lens, int64_t B,
             int32_t L, uint8_t* out) {
  int64_t off = 0;
  for (int64_t i = 0; i < B; i++) {
    const int32_t len = lens[i];
    __builtin_memcpy(out + i * L, joined + off, (size_t)len);
    off += len;
  }
}

}  // extern "C"
