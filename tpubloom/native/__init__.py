"""ctypes loader for the native C++ hash library (builds on first import).

No pybind11 in the environment, so the boundary is plain C ABI + ctypes
(SURVEY.md §2.1 native-component obligation). Everything degrades gracefully:
``HAS_NATIVE`` is False and callers fall back to the NumPy oracle if g++ or
the build is unavailable.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np
from tpubloom.utils import locks

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "bloomhash.cpp")
_LIB_PATH = os.path.join(_HERE, "libbloomhash.so")

_lock = locks.named_lock("native.build")
_lib = None
_load_failed = False  # negative cache: never re-fork a failing compiler
HAS_NATIVE = False


def _build() -> bool:
    cmd = [
        "g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17",
        _SRC, "-o", _LIB_PATH,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (subprocess.SubprocessError, FileNotFoundError):
        return False


def _load():
    global _lib, HAS_NATIVE, _load_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _load_failed:
            return None
        if not os.path.exists(_LIB_PATH) or os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC):
            if not _build():
                _load_failed = True
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            _load_failed = True
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i32p = ctypes.POINTER(ctypes.c_int32)
        u32p = ctypes.POINTER(ctypes.c_uint32)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        lib.bh_murmur3_batch.argtypes = [u8p, i32p, ctypes.c_int64, ctypes.c_int32, ctypes.c_uint32, u32p]
        lib.bh_fnv1a_batch.argtypes = [u8p, i32p, ctypes.c_int64, ctypes.c_int32, u32p]
        lib.bh_positions.argtypes = [u8p, i32p, ctypes.c_int64, ctypes.c_int32, ctypes.c_uint64, ctypes.c_int32, ctypes.c_uint32, u64p]
        lib.bh_insert.argtypes = [u32p, u64p, ctypes.c_int64]
        lib.bh_query.argtypes = [u32p, u64p, ctypes.c_int64, ctypes.c_int32, u8p]
        lib.bh_hash_insert.argtypes = [u32p, u8p, i32p, ctypes.c_int64, ctypes.c_int32, ctypes.c_uint64, ctypes.c_int32, ctypes.c_uint32]
        lib.bh_hash_query.argtypes = [u32p, u8p, i32p, ctypes.c_int64, ctypes.c_int32, ctypes.c_uint64, ctypes.c_int32, ctypes.c_uint32, u8p]
        lib.bh_blocked_insert.argtypes = [u32p, u8p, i32p, ctypes.c_int64, ctypes.c_int32, ctypes.c_uint64, ctypes.c_int32, ctypes.c_int32, ctypes.c_uint32, ctypes.c_int32]
        lib.bh_blocked_query.argtypes = [u32p, u8p, i32p, ctypes.c_int64, ctypes.c_int32, ctypes.c_uint64, ctypes.c_int32, ctypes.c_int32, ctypes.c_uint32, ctypes.c_int32, u8p]
        lib.bh_pack.argtypes = [u8p, i32p, ctypes.c_int64, ctypes.c_int32, u8p]
        _lib = lib
        HAS_NATIVE = True
        return lib


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def available() -> bool:
    return _load() is not None


def murmur3_batch(keys: np.ndarray, lens: np.ndarray, seed: int) -> np.ndarray:
    lib = _load()
    assert lib is not None
    keys = np.ascontiguousarray(keys, dtype=np.uint8)
    lens = np.ascontiguousarray(lens, dtype=np.int32)
    B, L = keys.shape
    out = np.empty(B, dtype=np.uint32)
    lib.bh_murmur3_batch(
        _ptr(keys, ctypes.c_uint8), _ptr(lens, ctypes.c_int32), B, L,
        ctypes.c_uint32(seed), _ptr(out, ctypes.c_uint32),
    )
    return out


def fnv1a_batch(keys: np.ndarray, lens: np.ndarray) -> np.ndarray:
    lib = _load()
    assert lib is not None
    keys = np.ascontiguousarray(keys, dtype=np.uint8)
    lens = np.ascontiguousarray(lens, dtype=np.int32)
    B, L = keys.shape
    out = np.empty(B, dtype=np.uint32)
    lib.bh_fnv1a_batch(
        _ptr(keys, ctypes.c_uint8), _ptr(lens, ctypes.c_int32), B, L,
        _ptr(out, ctypes.c_uint32),
    )
    return out


def positions_batch(keys: np.ndarray, lens: np.ndarray, *, m: int, k: int, seed: int) -> np.ndarray:
    lib = _load()
    assert lib is not None
    keys = np.ascontiguousarray(keys, dtype=np.uint8)
    lens = np.ascontiguousarray(lens, dtype=np.int32)
    B, L = keys.shape
    out = np.empty((B, k), dtype=np.uint64)
    lib.bh_positions(
        _ptr(keys, ctypes.c_uint8), _ptr(lens, ctypes.c_int32), B, L,
        ctypes.c_uint64(m), k, ctypes.c_uint32(seed), _ptr(out, ctypes.c_uint64),
    )
    return out


def hash_insert(words: np.ndarray, keys: np.ndarray, lens: np.ndarray, *, m: int, k: int, seed: int) -> None:
    lib = _load()
    assert lib is not None
    keys = np.ascontiguousarray(keys, dtype=np.uint8)
    lens = np.ascontiguousarray(lens, dtype=np.int32)
    B, L = keys.shape
    lib.bh_hash_insert(
        _ptr(words, ctypes.c_uint32), _ptr(keys, ctypes.c_uint8),
        _ptr(lens, ctypes.c_int32), B, L, ctypes.c_uint64(m), k,
        ctypes.c_uint32(seed),
    )


def _check_chunk_pool(block_bits: int, k: int, block_hash: str) -> None:
    """The chunk spec slices k positions out of the 96-bit (h_b, g_a, g_b)
    pool; the C++ side indexes pool[3] unchecked, so validate here exactly
    like cpu_ref.blocked_positions_np / FilterConfig do."""
    if block_hash == "chunk":
        nb = (block_bits - 1).bit_length()
        if k * nb > 96:
            raise ValueError(
                f"block_hash='chunk' needs k*log2(block_bits) <= 96 "
                f"(k={k}, {nb} bits/position) — use 'ap'"
            )


def blocked_insert(words: np.ndarray, keys: np.ndarray, lens: np.ndarray, *, n_blocks: int, block_bits: int, k: int, seed: int, block_hash: str = "ap") -> None:
    """Fused blocked-spec insert into ``uint32[n_blocks, W]`` (in place)."""
    _check_chunk_pool(block_bits, k, block_hash)
    lib = _load()
    assert lib is not None
    keys = np.ascontiguousarray(keys, dtype=np.uint8)
    lens = np.ascontiguousarray(lens, dtype=np.int32)
    B, L = keys.shape
    lib.bh_blocked_insert(
        _ptr(words, ctypes.c_uint32), _ptr(keys, ctypes.c_uint8),
        _ptr(lens, ctypes.c_int32), B, L, ctypes.c_uint64(n_blocks),
        block_bits, k, ctypes.c_uint32(seed), int(block_hash == "chunk"),
    )


def blocked_query(words: np.ndarray, keys: np.ndarray, lens: np.ndarray, *, n_blocks: int, block_bits: int, k: int, seed: int, block_hash: str = "ap") -> np.ndarray:
    _check_chunk_pool(block_bits, k, block_hash)
    lib = _load()
    assert lib is not None
    keys = np.ascontiguousarray(keys, dtype=np.uint8)
    lens = np.ascontiguousarray(lens, dtype=np.int32)
    B, L = keys.shape
    out = np.empty(B, dtype=np.uint8)
    lib.bh_blocked_query(
        _ptr(words, ctypes.c_uint32), _ptr(keys, ctypes.c_uint8),
        _ptr(lens, ctypes.c_int32), B, L, ctypes.c_uint64(n_blocks),
        block_bits, k, ctypes.c_uint32(seed), int(block_hash == "chunk"),
        _ptr(out, ctypes.c_uint8),
    )
    return out


def hash_query(words: np.ndarray, keys: np.ndarray, lens: np.ndarray, *, m: int, k: int, seed: int) -> np.ndarray:
    lib = _load()
    assert lib is not None
    keys = np.ascontiguousarray(keys, dtype=np.uint8)
    lens = np.ascontiguousarray(lens, dtype=np.int32)
    B, L = keys.shape
    out = np.empty(B, dtype=np.uint8)
    lib.bh_hash_query(
        _ptr(words, ctypes.c_uint32), _ptr(keys, ctypes.c_uint8),
        _ptr(lens, ctypes.c_int32), B, L, ctypes.c_uint64(m), k,
        ctypes.c_uint32(seed), _ptr(out, ctypes.c_uint8),
    )
    return out


def pack_joined(joined: bytes, lens: np.ndarray, key_len: int) -> np.ndarray:
    """Scatter a concatenated key buffer into a zero-padded
    ``uint8[B, key_len]`` matrix (the C++ ingest hot loop)."""
    lib = _load()
    assert lib is not None
    lens = np.ascontiguousarray(lens, dtype=np.int32)
    B = lens.shape[0]
    if B:
        if int(lens.min()) < 0 or int(lens.max()) > key_len:
            raise ValueError(
                f"lens must be in [0, key_len={key_len}]; "
                f"got [{int(lens.min())}, {int(lens.max())}]"
            )
        if int(lens.sum()) != len(joined):
            raise ValueError(
                f"joined buffer is {len(joined)} bytes but lens sum to "
                f"{int(lens.sum())}"
            )
    out = np.zeros((B, key_len), dtype=np.uint8)
    src = np.frombuffer(joined, dtype=np.uint8)
    lib.bh_pack(
        _ptr(src, ctypes.c_uint8), _ptr(lens, ctypes.c_int32), B, key_len,
        _ptr(out, ctypes.c_uint8),
    )
    return out
