"""Bloom-filter parameter math: (capacity, error_rate) -> (m, k).

Parity: the reference front-end computes optimal ``m`` (bits) and ``k`` (hash
count) from desired capacity + error rate with the textbook formulas
``m = -n·ln(p)/ln(2)²`` and ``k = (m/n)·ln(2)`` (SURVEY.md §2.1,
"Parameter math", expected in lib/redis-bloomfilter.rb [PK]; pinned by
BASELINE.json north_star which fixes m=2^32, k=7 at ≤1% FPR).

Kept dependency-free (pure ``math``) so the Ruby client, the CPU oracle and
the device kernels can all share one source of truth for sizing.
"""

from __future__ import annotations

import math


def optimal_m_k(capacity: int, error_rate: float) -> tuple[int, int]:
    """Return ``(m, k)`` — bit-array size and hash count — for a filter that
    holds ``capacity`` keys at false-positive probability ``error_rate``.

    ``m = ceil(-n·ln(p) / ln(2)²)``, ``k = max(1, round((m/n)·ln(2)))``.
    """
    if capacity <= 0:
        raise ValueError(f"capacity must be positive, got {capacity}")
    if not (0.0 < error_rate < 1.0):
        raise ValueError(f"error_rate must be in (0, 1), got {error_rate}")
    n = float(capacity)
    p = float(error_rate)
    m = math.ceil(-n * math.log(p) / (math.log(2.0) ** 2))
    k = max(1, round((m / n) * math.log(2.0)))
    return m, k


def theoretical_fpr(m: int, k: int, n: int) -> float:
    """Expected false-positive rate after inserting ``n`` keys:
    ``(1 - e^(-k·n/m))^k``."""
    if n == 0:
        return 0.0
    return (1.0 - math.exp(-k * n / m)) ** k


def round_up_pow2(x: int) -> int:
    """Smallest power of two >= x (device-friendly m; pow2 m enables the
    64-bit position path and turns mod into a bit mask)."""
    if x <= 1:
        return 1
    return 1 << (x - 1).bit_length()
