"""Bloom-filter parameter math: (capacity, error_rate) -> (m, k).

Parity: the reference front-end computes optimal ``m`` (bits) and ``k`` (hash
count) from desired capacity + error rate with the textbook formulas
``m = -n·ln(p)/ln(2)²`` and ``k = (m/n)·ln(2)`` (SURVEY.md §2.1,
"Parameter math", expected in lib/redis-bloomfilter.rb [PK]; pinned by
BASELINE.json north_star which fixes m=2^32, k=7 at ≤1% FPR).

Kept dependency-free (pure ``math``) so the Ruby client, the CPU oracle and
the device kernels can all share one source of truth for sizing.
"""

from __future__ import annotations

import math


def optimal_m_k(capacity: int, error_rate: float) -> tuple[int, int]:
    """Return ``(m, k)`` — bit-array size and hash count — for a filter that
    holds ``capacity`` keys at false-positive probability ``error_rate``.

    ``m = ceil(-n·ln(p) / ln(2)²)``, ``k = max(1, round((m/n)·ln(2)))``.
    """
    if capacity <= 0:
        raise ValueError(f"capacity must be positive, got {capacity}")
    if not (0.0 < error_rate < 1.0):
        raise ValueError(f"error_rate must be in (0, 1), got {error_rate}")
    n = float(capacity)
    p = float(error_rate)
    m = math.ceil(-n * math.log(p) / (math.log(2.0) ** 2))
    k = max(1, round((m / n) * math.log(2.0)))
    return m, k


def theoretical_fpr(m: int, k: int, n: int) -> float:
    """Expected false-positive rate after inserting ``n`` keys:
    ``(1 - e^(-k·n/m))^k``."""
    if n == 0:
        return 0.0
    return (1.0 - math.exp(-k * n / m)) ** k


def _distinct_distribution(k: int, b: int) -> list[float]:
    """P(D = d): distribution of the number of DISTINCT values among k
    i.i.d. uniforms over b bins — ``P(D=d) = S2(k,d) · b!/(b-d)! / b^k``
    with S2 the Stirling numbers of the second kind."""
    # S2 via the triangle recurrence
    s2 = [[0.0] * (k + 1) for _ in range(k + 1)]
    s2[0][0] = 1.0
    for i in range(1, k + 1):
        for d in range(1, i + 1):
            s2[i][d] = s2[i - 1][d - 1] + d * s2[i - 1][d]
    out = [0.0] * (k + 1)
    for d in range(1, k + 1):
        falling = 1.0
        for j in range(d):
            falling *= (b - j) / b
        out[d] = s2[k][d] * falling * b ** (d - k)
    return out


def blocked_fpr(
    n: int,
    *,
    m: int,
    k: int,
    block_bits: int,
    block_hash: str = "chunk",
    tail_sigmas: float = 12.0,
) -> float:
    """Expected false-positive rate of the BLOCKED layout after ``n`` keys.

    The blocked spec (tpubloom.ops.blocked) confines all k bits of a key
    to one ``block_bits``-bit block, so per-block load is
    ``L ~ Poisson(lambda = n / n_blocks)`` and the filter is a Poisson
    mixture of tiny b-bit bloom filters:

        FPR = E_L[ f(L) ],   b = block_bits.

    For ``block_hash="chunk"`` positions are i.i.d. uniform, so a block
    bit survives one insert with probability (1 - 1/b)^k exactly, and a
    query testing D distinct positions (D per the Stirling distribution
    of k uniforms) hits with

        f(L) = E_D[ (1 - (1 - 1/b)^(k·L))^D ].

    For ``block_hash="ap"`` each key's positions are k DISTINCT residues
    of an odd-stride walk, giving f(L) = (1 - (1 - k/b)^L)^k — PLUS the
    AP family floor: the position set is determined by the ~2·log2(b)-bit
    pair (g_a mod b, g_b mod b), and a query whose pair matches an insert
    in its block (same AP, or the reversed AP) shares every position:

        floor ≈ lambda · 4 / b²

    (two set-equal (start, stride) pairs out of b·(b/2); partial-AP
    overlap adds ~25% more in measurement, so this is a lower bound —
    measured 1.6e-4 total vs 1.3e-4 floor at m=2^32, b=512, lambda=8.6,
    where the mixture alone says 1e-6). This floor is linear in load and
    does NOT vanish at low fill; it is why "chunk" is the default spec.

    Jensen's inequality makes the mixture >= the flat ``theoretical_fpr``
    at equal fill (block loads are skewed); the expected OVERALL fill is
    identical (E[1 - (1-k/b)^L] = 1 - e^(-k n / m)). The Poisson sum is
    truncated at ``lambda + tail_sigmas * sqrt(lambda)`` which bounds the
    truncated mass far below the returned value's precision.
    """
    if n == 0:
        return 0.0
    b = block_bits
    if b <= 0 or b & (b - 1) or b < k:
        raise ValueError(f"block_bits must be a power of two >= k, got {b}")
    n_blocks = m // b
    lam = n / n_blocks
    lmax = int(lam + tail_sigmas * math.sqrt(lam) + 16)
    if block_hash == "chunk":
        pd = _distinct_distribution(k, b)
        unset_per_insert = (1.0 - 1.0 / b) ** k

        def f(L: int) -> float:
            q = 1.0 - unset_per_insert**L
            return sum(pd[d] * q**d for d in range(1, k + 1))

    elif block_hash == "ap":
        per_key_unset = 1.0 - k / b

        def f(L: int) -> float:
            q = 1.0 - per_key_unset**L
            return q**k

    else:
        raise ValueError(f"block_hash must be 'chunk' or 'ap', got {block_hash!r}")
    # Poisson pmf iteratively (avoids factorial overflow at large lambda)
    log_p = -lam  # log pmf at L=0
    total = 0.0
    for L in range(lmax + 1):
        if L > 0:
            log_p += math.log(lam) - math.log(L)
        total += math.exp(log_p) * f(L)
    if block_hash == "ap":
        total += lam * 4.0 / (b * b)  # family floor (see docstring)
    return total


def round_up_pow2(x: int) -> int:
    """Smallest power of two >= x (device-friendly m; pow2 m enables the
    64-bit position path and turns mod into a bit mask)."""
    if x <= 1:
        return 1
    return 1 << (x - 1).bit_length()
