# Driver::JaxCluster — the cluster-aware :jax driver (ISSUE 9).
#
# Same duck-typed contract as Driver::Jax (#insert, #include?, #delete,
# #clear, the batch surface), but against a tpubloom CLUSTER: the filter
# name hashes to one of 16384 slots (CRC16-XMODEM mod 16384, Redis
# Cluster's exact function, {hash tag} extraction included), the driver
# bootstraps the slot→node map from any node's ClusterSlots answer, and
# the two redirect kinds heal transparently:
#
#   MOVED <slot> <addr> — ownership changed (a finalized migration or a
#     stale map): re-fetch the map, reconnect to the new owner, retry;
#   ASK <slot> <addr>   — slot mid-migration and the filter already
#     lives at the target: ONE follow-up flagged "asking" goes to the
#     target, no map update (the source still owns the slot).
#
# MIGRATE_FORWARD_FAILED (the write applied on the source but its
# dual-write forward is still in flight) is re-driven under the SAME
# rid — the server answers the replay from its dedup cache and forwards
# again, so counting filters never double-apply.
#
# opts adds to Driver::Jax's:
#   :cluster_nodes - ["host:port", ...] of any cluster nodes (the map
#                    bootstrap set; the live owner is resolved per the
#                    map, so this list only needs one reachable node)
#
# NOTE: written against the documented server protocol but UNTESTED in
# the build environment (no Ruby toolchain in the image); the identical
# wire format and redirect flow is exercised end-to-end by the Python
# ClusterClient (tests/test_cluster.py).

require_relative "jax"

class Redis
  class Bloomfilter
    module Driver
      class JaxCluster < Jax
        NUM_SLOTS = 16_384

        CRC16_TABLE = (0...256).map do |byte|
          crc = byte << 8
          8.times do
            crc = ((crc & 0x8000).zero? ? crc << 1 : (crc << 1) ^ 0x1021) & 0xFFFF
          end
          crc
        end.freeze

        def self.crc16(data)
          crc = 0
          data.each_byte do |b|
            crc = ((crc << 8) & 0xFFFF) ^ CRC16_TABLE[((crc >> 8) ^ b) & 0xFF]
          end
          crc
        end

        # Redis hash-tag rule: a non-empty {...} body hashes alone, so
        # user:{42}:seen and user:{42}:blocked share a slot.
        def self.key_slot(name)
          raw = name.to_s.b
          if (start = raw.index("{")) && (stop = raw.index("}", start + 1)) &&
             stop > start + 1
            raw = raw[(start + 1)...stop]
          end
          crc16(raw) % NUM_SLOTS
        end

        def initialize(opts = {})
          @cluster_nodes = Array(opts[:cluster_nodes])
          raise ArgumentError, "need :cluster_nodes" if @cluster_nodes.empty?
          @slot = self.class.key_slot(opts[:key_name] || "tpubloom")
          owner = resolve_owner || @cluster_nodes.first
          super(opts.merge(address: owner))
        end

        # -- cluster admin surface (CLUSTER SETSLOT / live-migration
        # parity with the Python client; part of the ruby-parity check
        # in python -m tpubloom.analysis.lint) -------------------------

        # The connected node's slot map ({enabled, epoch, ranges, ...}).
        def cluster_slots
          rpc("ClusterSlots", {}, no_retry: true)
        end

        # Admin verb: slot=/state=/addr= or the bulk
        # assign=[[start, stop, addr], ...] + epoch= form.
        def cluster_set_slot(req)
          rpc("ClusterSetSlot", req, no_retry: true)
        end

        # Drive the live migration of `slot` from the connected node
        # (its owner) to `target`; blocks until the handoff finalizes.
        def migrate_slot(slot, target)
          rpc(
            "MigrateSlot", { "slot" => slot.to_i, "target" => target },
            no_retry: true
          )
        end

        # Resume probe of a migration target's import gate for one
        # filter ({"have" => <source seq> | nil}) — the node→node
        # MigrateInstall hop's read-only form, exposed for tooling.
        def migrate_install_probe(name)
          rpc(
            "MigrateInstall", { "name" => name, "probe" => true },
            no_retry: true
          )
        end

        # Cross-node trace assembly (ISSUE 15, the Python
        # ClusterClient#trace twin): merge TraceGet answers from every
        # bootstrap node for `rid`, then follow the trace ids the
        # returned spans introduce (a coalescer flush span links the
        # rid, but its kernel phases / barrier / replica applies live
        # under the FLUSH trace id) — one extra fan-out round.
        def trace(rid = nil)
          rid ||= @last_rid
          spans = {}
          pending = [rid].compact
          seen = []
          2.times do
            fresh = pending.uniq - seen
            break if fresh.empty?
            fresh.each do |tid|
              seen << tid
              @cluster_nodes.each do |addr|
                stub = GRPC::ClientStub.new(addr, :this_channel_is_insecure)
                begin
                  raw = stub.request_response(
                    "/#{SERVICE}/TraceGet",
                    { "trace_rid" => tid }.to_msgpack, IDENTITY, IDENTITY
                  )
                  resp = MessagePack.unpack(raw)
                  next unless resp["ok"]
                  (resp["spans"] || []).each do |s|
                    spans[[s["rid"], s["span"]]] = s
                    pending << s["rid"] if s["rid"]
                  end
                rescue GRPC::BadStatus
                  next
                end
              end
            end
          end
          spans.values.sort_by { |s| s["start"] || 0.0 }
        end

        private

        # The freshest ClusterSlots answer across the bootstrap nodes;
        # returns our slot's owner address (nil when no node answers).
        def resolve_owner
          best = nil
          @cluster_nodes.each do |addr|
            stub = GRPC::ClientStub.new(addr, :this_channel_is_insecure)
            begin
              raw = stub.request_response(
                "/#{SERVICE}/ClusterSlots", {}.to_msgpack, IDENTITY, IDENTITY
              )
              resp = MessagePack.unpack(raw)
              next unless resp["ok"] && resp["enabled"]
              best = resp if best.nil? || resp["epoch"].to_i > best["epoch"].to_i
            rescue GRPC::BadStatus
              next
            end
          end
          return nil unless best
          (best["ranges"] || []).each do |start, stop, addr|
            return addr if @slot.between?(start, stop)
          end
          nil
        end

        # Per-hop wire-encoding discipline (ISSUE 14 satellite — the
        # named PR-10 seam): the public batch methods encode against
        # the CURRENT connection's negotiation, but a MOVED/CLUSTERDOWN
        # reconnect re-sends the payload to a node that negotiated
        # nothing. If the new connection's Health probe does not
        # advertise `fixed`, demote a keys_fixed payload back to the
        # msgpack list for the retry hop. (ask_once's one-shot raw-stub
        # hop is not re-probed: within one fleet generation every node
        # decodes both encodings; the probe exists for rolling-upgrade
        # mixes, which the owner-map path above covers.)
        def demote_fixed(payload)
          fx = payload["keys_fixed"]
          return payload unless fx && !fixed_negotiated?
          data = fx["data"]
          width = fx["width"]
          keys = (0...fx["n"]).map { |i| data.byteslice(i * width, width) }
          payload = payload.reject { |k, _| k == "keys_fixed" }
          payload["keys"] = keys
          payload
        end

        # Layer the cluster redirects over Jax#rpc's retry machinery
        # (shed pacing, UNAVAILABLE backoff, NOT_FOUND heal all apply
        # per target node).
        def rpc(method, payload, no_retry: false)
          # stamp the logical call's rid HERE so every hop below — the
          # base driver's retries, ASK follow-ups, and forward re-drives
          # — shares it (the server's dedup cache keys on it; a fresh
          # rid per hop would double-apply counting inserts)
          payload = payload.merge("rid" => SecureRandom.hex(8))
          @last_rid = payload["rid"]
          # stamp trace context HERE too (not only in the base rpc): the
          # ASK / re-drive hops below ship `payload` through raw stubs
          # that bypass the base driver, and every hop of one logical
          # call must carry the same trace field as its rid (ISSUE 15)
          payload["trace"] = { "forced" => true } if @trace && !payload["trace"]
          redirects = 0
          begin
            super
          rescue ServiceError => e
            case e.code
            when "MOVED"
              raise if redirects >= 5
              redirects += 1
              connect(e.details["addr"] || resolve_owner)
              payload = demote_fixed(payload)
              retry
            when "ASK"
              ask_once(method, payload, e.details["addr"])
            when "CLUSTERDOWN"
              raise if redirects >= 5
              redirects += 1
              owner = resolve_owner
              connect(owner) if owner
              payload = demote_fixed(payload)
              sleep(0.1 * redirects)
              retry
            when "MIGRATE_FORWARD_FAILED"
              # applied on the source, forward pending: re-drive the
              # SAME rid until the dual-write lands (dedup-safe); the
              # error's src_seq rides along so a post-finalize MOVED
              # follow-up is still judged by the new owner's import
              # gate (a record the snapshot contains must dup out)
              redrive(method, payload, e.details["src_seq"])
            else
              raise
            end
          end
        end

        # One ASKING follow-up at the migration target (Redis ASK
        # semantics: no map update, the source still owns the slot).
        def ask_once(method, payload, addr, src_seq = nil)
          stub = GRPC::ClientStub.new(addr, :this_channel_is_insecure)
          followup = payload.merge("asking" => true)
          followup["src_seq"] = src_seq if src_seq
          raw = stub.request_response(
            "/#{SERVICE}/#{method}",
            followup.to_msgpack,
            IDENTITY,
            IDENTITY
          )
          resp = MessagePack.unpack(raw)
          unless resp["ok"]
            err = resp["error"] || {}
            raise ServiceError.new(
              err["code"] || "UNKNOWN", err["message"], err["details"]
            )
          end
          resp
        end

        def redrive(method, payload, src_seq = nil)
          30.times do |i|
            sleep([0.05 * (i + 1), 1.0].min)
            begin
              return rpc_once(method, payload)
            rescue ServiceError => e
              case e.code
              when "MIGRATE_FORWARD_FAILED"
                src_seq = e.details["src_seq"] || src_seq
                next
              when "MOVED", "ASK"
                return ask_once(method, payload, e.details["addr"], src_seq)
              else
                raise
              end
            rescue GRPC::BadStatus
              next
            end
          end
          raise ServiceError.new(
            "MIGRATE_FORWARD_FAILED", "re-drive budget exhausted", {}
          )
        end
      end
    end
  end
end
