# Driver::Jax — the :jax execution driver for Redis::Bloomfilter.
#
# Parity: plugs into the reference's driver-selection boundary
# (SURVEY.md §1 L2: ":ruby / :lua -> new :jax"; BASELINE.json north star).
# Same duck-typed contract as the :ruby and :lua drivers — #insert,
# #include?, #clear — plus the batch surface the north star adds:
# #insert_batch and #include_batch?. Instead of issuing SETBIT/GETBIT (or
# EVALSHA) against Redis, every call ships key batches over gRPC to the
# colocated tpubloom JAX process, which holds the bit array in TPU HBM and
# checkpoints it back to Redis in the reference's own bitmap format (so a
# :ruby-driver reader still works against the checkpoint).
#
# Wire format: gRPC unary calls on /tpubloom.BloomService/<Method> with
# msgpack-encoded maps (see tpubloom/server/protocol.py — the environment
# that generated the server has no protoc codegen, and msgpack-ruby is
# ubiquitous). Requires gems: grpc, msgpack.
#
# NOTE: written against the documented server protocol but UNTESTED in the
# build environment (no Ruby toolchain in the image); exercised end-to-end
# via the Python client, which speaks the identical wire format.

require "grpc"
require "msgpack"
require "securerandom"

class Redis
  class Bloomfilter
    module Driver
      class Jax
        SERVICE = "tpubloom.BloomService".freeze
        # The FULL unary surface of the tpubloom protocol — kept in
        # lockstep with tpubloom/server/protocol.py METHODS by the
        # `ruby-parity` check in `python -m tpubloom.analysis.lint`
        # (every entry must also have a call site in this driver or the
        # cluster driver; drift fails CI).
        METHODS = %w[
          Health CreateFilter DropFilter ListFilters
          InsertBatch QueryBatch DeleteBatch Clear Stats Checkpoint Wait
          SlowlogGet SlowlogReset TraceGet Promote ReplicaOf
          ClusterSlots ClusterSetSlot MigrateSlot MigrateInstall
          CFReserve CFAdd CFDel CFExists
          CMSInitByDim CMSIncrBy CMSQuery
          TopKReserve TopKAdd TopKList
        ].freeze

        IDENTITY = proc { |bytes| bytes }

        # Structured server-side error (protocol error_response): code,
        # message, and optional machine-readable details (e.g. the
        # retry_after_ms hint on overload sheds).
        class ServiceError < RuntimeError
          attr_reader :code, :details

          def initialize(code, message, details = {})
            super("tpubloom #{code}: #{message}")
            @code = code
            @details = details || {}
          end
        end

        # Codes meaning "the server refused BEFORE running the handler" —
        # replaying is safe for every method, idempotent or not.
        SHED_CODES = %w[RESOURCE_EXHAUSTED DRAINING].freeze

        # DeleteBatch is auto-retried since ISSUE 2: each logical call
        # carries a rid that retries reuse, and the server's rid->response
        # dedup cache answers a replay whose first attempt landed instead
        # of double-decrementing. Counting/presence INSERTS remain
        # non-retried on transport errors (scatter-ADDs; no dedup there).

        SENTINEL_SERVICE = "tpubloom.Sentinel".freeze

        # opts mirrors the reference constructor options plus:
        #   :address       - "host:port" of the tpubloom server (default
        #                    127.0.0.1:50051)
        #   :sentinels     - ["host:port", ...] of tpubloom sentinels: the
        #                    driver resolves the current primary (and the
        #                    topology epoch) from them at startup and
        #                    REFRESHES on READONLY / STALE_EPOCH /
        #                    exhausted-UNAVAILABLE — writes fail over to
        #                    the newly promoted primary; the per-call rid
        #                    makes a re-driven acknowledged batch answer
        #                    from the server's dedup cache instead of
        #                    double-applying
        #   :size          - expected capacity (n)
        #   :error_rate    - desired false-positive probability
        #   :key_name      - filter name (also the Redis checkpoint key)
        #   :counting      - use the counting variant (enables #delete)
        #   :max_retries   - UNAVAILABLE retry budget (default 5); retried
        #                    ops are idempotent bloom ops, with exponential
        #                    backoff + jitter. On NOT_FOUND after a server
        #                    restart the driver transparently re-creates the
        #                    filter (the server restores its newest
        #                    checkpoint) and retries once.
        #   :encoding      - "auto" (default) ships fixed-width key
        #                    batches (every key the same byte length) as
        #                    the zero-copy `fixed` wire encoding once a
        #                    Health probe confirmed the server supports
        #                    it (negotiated per-connection, re-probed
        #                    after a failover re-point); "msgpack" pins
        #                    the classic per-key list
        #   :trace         - true to force distributed-trace capture for
        #                    every call this driver makes (ISSUE 15): each
        #                    request carries trace => {forced: true}, so a
        #                    --trace-sample-armed server records the full
        #                    span tree under the call's rid regardless of
        #                    its sample rate; #trace_get(rid) fetches the
        #                    connected node's spans. Default off: no wire
        #                    field is added (identical bytes to older
        #                    drivers).
        #   :min_replicas  - default durability quorum stamped on every
        #                    mutating call (Redis min-replicas-to-write
        #                    parity, ISSUE 5): the server blocks the call
        #                    after its op-log append until that many
        #                    replicas acknowledged the record; a timeout
        #                    raises ServiceError NOT_ENOUGH_REPLICAS (the
        #                    write applied and is logged — only the quorum
        #                    ack is missing). Per-call overrides via the
        #                    min_replicas: kwarg; #wait is the WAIT-parity
        #                    after-the-fact probe.
        def initialize(opts = {})
          @opts = opts
          @name = opts[:key_name] || "tpubloom"
          @max_retries = opts[:max_retries] || 5
          @sentinels = Array(opts[:sentinels])
          @epoch = nil
          @min_replicas = opts[:min_replicas]
          @trace = !!opts[:trace]
          @last_rid = nil
          @last_write_seq = nil
          @encoding = opts[:encoding] || "auto"
          address = opts[:address] || "127.0.0.1:50051"
          if !@sentinels.empty? && (topo = fetch_topology)
            address = topo["primary"] || address
            @epoch = topo["epoch"]
          end
          connect(address)
          create_filter
        end

        def insert(key, min_replicas: nil)
          insert_batch([key], min_replicas: min_replicas)
        end

        def insert_batch(keys, min_replicas: nil)
          rpc(
            "InsertBatch",
            durability(
              encode_keys({ "name" => @name }, keys), min_replicas
            )
          )
          true
        end

        # Fused test-and-insert: inserts every key and returns an array of
        # booleans — true if the key was ALREADY present before this batch
        # (the :lua driver's add-script semantics, batched). Never
        # auto-retried: a replay after a landed insert would report the
        # batch's own keys as duplicates.
        def insert_batch_was_present?(keys, min_replicas: nil)
          resp = rpc(
            "InsertBatch",
            durability(
              encode_keys(
                { "name" => @name, "return_presence" => true }, keys
              ), min_replicas
            ),
            no_retry: true
          )
          unpack_bits(resp["presence"], resp["n"])
        end

        def include?(key)
          include_batch?([key]).first
        end

        # Returns an array of booleans, one per key.
        def include_batch?(keys)
          resp = rpc("QueryBatch", encode_keys({ "name" => @name }, keys))
          unpack_bits(resp["hits"], resp["n"])
        end

        def delete(key, min_replicas: nil)
          delete_batch([key], min_replicas: min_replicas)
        end

        def delete_batch(keys, min_replicas: nil)
          # rides the zero-copy `fixed` encoding like inserts/queries
          # (ISSUE 14 satellite — was the last msgpack-only key path)
          rpc(
            "DeleteBatch",
            durability(encode_keys({ "name" => @name }, keys), min_replicas)
          )
          true
        end

        def clear(min_replicas: nil)
          rpc("Clear", durability({ "name" => @name }, min_replicas))
          true
        end

        # Redis WAIT parity (ISSUE 5): block until numreplicas replicas
        # acknowledged this driver's last write, up to timeout_ms; returns
        # the count actually acked — possibly fewer (WAIT reports, it does
        # not raise).
        def wait(numreplicas, timeout_ms = 1000)
          req = { "numreplicas" => numreplicas, "timeout_ms" => timeout_ms }
          req["seq"] = @last_write_seq if @last_write_seq
          rpc("Wait", req)["nreplicas"]
        end

        def stats
          rpc("Stats", "name" => @name)["stats"]
        end

        def checkpoint
          rpc("Checkpoint", "name" => @name, "wait" => true)["seq"]
        end

        # -- admin / observability surface (protocol parity — the same
        # verbs the Python client exposes; ROADMAP item 6 asks the Ruby
        # drivers to cover the whole METHODS registry) -----------------

        def drop_filter(final_checkpoint: true)
          rpc(
            "DropFilter",
            { "name" => @name, "final_checkpoint" => final_checkpoint }
          )
          true
        end

        def list_filters
          rpc("ListFilters", {})["filters"]
        end

        # Redis SLOWLOG GET parity: slowest requests first, each with
        # method/args/duration/rid + the per-phase breakdown.
        def slowlog_get(n = nil)
          req = n ? { "n" => n } : {}
          rpc("SlowlogGet", req)["entries"]
        end

        def slowlog_reset
          rpc("SlowlogReset", {})["cleared"]
        end

        # Distributed-tracing lookup (ISSUE 15): the spans the connected
        # node recorded for one rid (default: this driver's last call),
        # plus coalescer flush spans that link it. Pair with :trace =>
        # true so the server captures regardless of its sample rate.
        # (trace_rid, not rid: the bare rid field is the per-call
        # transport correlation id this driver stamps, which would
        # clobber the lookup key.)
        def trace_get(rid = nil)
          rpc("TraceGet", { "trace_rid" => rid || @last_rid })["spans"]
        end

        # HA admin verbs (REPLICAOF NO ONE / REPLICAOF parity). Raw
        # node-level operations: they act on the CONNECTED node, not on
        # the logical filter, and are never auto-retried (a replayed
        # promotion under a bumped epoch answers STALE_EPOCH).
        def promote!(epoch: nil, repl_log_dir: nil)
          req = {}
          req["epoch"] = epoch if epoch
          req["repl_log_dir"] = repl_log_dir if repl_log_dir
          rpc("Promote", req, no_retry: true)
        end

        def replica_of!(primary, epoch: nil)
          req = { "primary" => primary }
          req["epoch"] = epoch if epoch
          rpc("ReplicaOf", req, no_retry: true)
        end

        # -- sketch plane (ISSUE 19): RedisBloom CF.*/CMS.*/TOPK. parity
        #
        # Kind-specific verbs on NAMED sketches (a driver instance is
        # bound to one bloom filter via :key_name, but sketches are
        # sibling keys — so every sketch verb takes the name
        # explicitly, mirroring the RedisBloom command shapes).

        # CF.RESERVE: create a cuckoo filter sized for capacity keys.
        def cf_reserve(name, capacity, **options)
          req = { "name" => name, "capacity" => capacity, "exist_ok" => true }
          req["options"] = options unless options.empty?
          rpc("CFReserve", req)
          true
        end

        # CF.ADD (batched): one boolean per key — false where the
        # honestly-FULL table rejected the insert. Never auto-retried:
        # cuckoo inserts are multiset adds with no idempotent replay.
        def cf_add(name, keys, min_replicas: nil)
          resp = rpc(
            "CFAdd",
            durability(encode_keys({ "name" => name }, keys), min_replicas),
            no_retry: true
          )
          return Array.new(resp["n"], true) unless resp["full"]
          unpack_bits(resp["full"], resp["n"]).map { |rejected| !rejected }
        end

        # CF.DEL (batched): removes ONE stored copy per key; returns one
        # boolean per key — true where a copy existed. Retries reuse the
        # rid and the server's dedup cache absorbs replays.
        def cf_del(name, keys, min_replicas: nil)
          resp = rpc(
            "CFDel",
            durability(encode_keys({ "name" => name }, keys), min_replicas)
          )
          unpack_bits(resp["deleted"], resp["n"])
        end

        # CF.EXISTS (batched): no false negatives.
        def cf_exists?(name, keys)
          resp = rpc("CFExists", encode_keys({ "name" => name }, keys))
          unpack_bits(resp["hits"], resp["n"])
        end

        # CMS.INITBYDIM: width rounds up server-side to a multiple of 32.
        def cms_init_by_dim(name, width, depth, **options)
          req = {
            "name" => name, "width" => width, "depth" => depth,
            "exist_ok" => true
          }
          req["options"] = options unless options.empty?
          rpc("CMSInitByDim", req)
          true
        end

        # CMS.INCRBY: weighted increments answer the post-update
        # estimates; unit increments (increments: nil) ride the
        # server's coalesced insert path and answer nil — follow with
        # #cms_query when the counts are needed. Weighted calls are
        # replay-guarded by the rid dedup cache server-side.
        def cms_incrby(name, keys, increments: nil, min_replicas: nil)
          req = durability(
            encode_keys({ "name" => name }, keys), min_replicas
          )
          req["increments"] = increments if increments
          rpc("CMSIncrBy", req)["counts"]
        end

        # CMS.QUERY: point estimates, each only ever >= the true count.
        def cms_query(name, keys)
          rpc("CMSQuery", { "name" => name, "keys" => keys.map(&:to_s) })["counts"]
        end

        # TOPK.RESERVE: top-k heavy hitters over a CMS backing array.
        def topk_reserve(name, topk, width: 2048, depth: 5, **options)
          req = {
            "name" => name, "topk" => topk, "width" => width,
            "depth" => depth, "exist_ok" => true
          }
          req["options"] = options unless options.empty?
          rpc("TopKReserve", req)
          true
        end

        # TOPK.ADD (unit counts). Never auto-retried — counting adds
        # have no idempotent replay; the rid dedup covers a landed
        # first flight.
        def topk_add(name, keys, min_replicas: nil)
          rpc(
            "TopKAdd",
            durability(encode_keys({ "name" => name }, keys), min_replicas),
            no_retry: true
          )["n"]
        end

        # TOPK.LIST WITHCOUNT: [[key, estimate], ...] descending.
        def topk_list(name)
          rpc("TopKList", { "name" => name })["items"].map do |item|
            [item["key"], item["count"]]
          end
        end

        # -- streaming ingest plane (ISSUE 18) -------------------------
        #
        # One persistent bidi RPC carries many seq-stamped key frames;
        # the server acks each frame with the full unary-shaped verdict
        # (acks echo the frame's seq and are NOT necessarily in frame
        # order — see BIDI_STREAM_METHODS in tpubloom/server/protocol.py).
        # This driver ignores the server's advisory credit grants: an
        # over-sending stream is PARKED by the server's bounded ingest
        # backpressure (gRPC/TCP flow control pushes back), never shed,
        # so correctness holds either way. Each frame keeps its own rid;
        # replaying a broken stream's unacked frames under those rids is
        # answered from the server's dedup cache (exactly-once).

        # Ship each key batch as one InsertStream frame; returns the
        # per-batch responses in batch order (raises ServiceError on the
        # first error verdict).
        def insert_stream(batches, min_replicas: nil, return_presence: false)
          stream_frames("InsertStream", batches) do |payload|
            payload["return_presence"] = true if return_presence
            durability(payload, min_replicas)
          end
        end

        # Ship each key batch as one QueryStream frame; returns one
        # boolean membership array per batch, in batch order.
        def query_stream(batches)
          stream_frames("QueryStream", batches).map do |resp|
            unpack_bits(resp["hits"], resp["n"])
          end
        end

        private

        def stream_frames(method, batches)
          seq = 0
          frames = batches.map do |keys|
            seq += 1
            payload = encode_keys(
              { "seq" => seq, "rid" => SecureRandom.hex(8), "name" => @name },
              keys
            )
            payload["epoch"] = @epoch if @epoch && method == "InsertStream"
            payload = yield(payload) || payload if block_given?
            payload
          end
          acks = {}
          responses = @stub.bidi_streamer(
            "/#{SERVICE}/#{method}",
            frames.map(&:to_msgpack).each,
            IDENTITY,
            IDENTITY
          )
          responses.each do |raw|
            frame = MessagePack.unpack(raw)
            next unless frame["kind"] == "ack"
            resp = frame["resp"] || {}
            @last_write_seq = resp["repl_seq"] if resp["repl_seq"]
            acks[frame["seq"]] = resp
          end
          (1..seq).map do |s|
            resp = acks[s] || {}
            unless resp["ok"]
              err = resp["error"] || {}
              raise ServiceError.new(
                err["code"] || "UNKNOWN", err["message"], err["details"]
              )
            end
            resp
          end
        end

        def connect(address)
          @address = address
          @stub = GRPC::ClientStub.new(address, :this_channel_is_insecure)
          # wire-encoding capability is per-CONNECTION (ISSUE 10): a
          # failover re-point must re-probe the new primary
          @fixed_negotiated = nil
        end

        # Lazy per-connection negotiation of the zero-copy `fixed` key
        # encoding: one Health probe decides; probe failures degrade to
        # msgpack for this connection, never an error.
        def fixed_negotiated?
          return false if @encoding == "msgpack"
          if @fixed_negotiated.nil?
            @fixed_negotiated =
              begin
                h = rpc_once("Health", {})
                Array(h["encodings"]).include?("fixed")
              rescue GRPC::BadStatus, ServiceError
                false
              end
          end
          @fixed_negotiated
        end

        # Fold the key batch into the payload under the best negotiated
        # encoding (ISSUE 10): when every key is the SAME byte length
        # and the server speaks `fixed`, the batch ships as one raw
        # buffer ({data, width, n}) the server decodes zero-copy;
        # anything else takes the classic msgpack list.
        def encode_keys(payload, keys)
          keys = keys.map(&:to_s)
          # tiny batches gain nothing from the fixed encoding and would
          # change the op-log record shape scalar calls produce — mirror
          # the Python client's FIXED_LIST_MIN threshold
          if keys.length >= 8 && fixed_negotiated?
            width = keys.first.bytesize
            if width.positive? && keys.all? { |k| k.bytesize == width }
              payload["keys_fixed"] = {
                "data" => keys.join.b, "width" => width, "n" => keys.length
              }
              return payload
            end
          end
          payload["keys"] = keys
          payload
        end

        # Ask each sentinel for the current cluster view; first answer
        # wins (SENTINEL get-master-addr-by-name parity).
        def fetch_topology
          @sentinels.each do |addr|
            stub = GRPC::ClientStub.new(addr, :this_channel_is_insecure)
            begin
              raw = stub.request_response(
                "/#{SENTINEL_SERVICE}/Topology",
                {}.to_msgpack,
                IDENTITY,
                IDENTITY
              )
              resp = MessagePack.unpack(raw)
              return resp if resp["ok"] && resp["primary"]
            rescue GRPC::BadStatus
              next
            end
          end
          nil
        end

        # Adopt the sentinels' view iff its epoch is not older than the
        # cached one; true iff the primary changed (retry should target
        # the new process).
        def refresh_topology
          return false if @sentinels.empty?
          topo = fetch_topology
          return false unless topo
          epoch = topo["epoch"] || 0
          return false if @epoch && epoch < @epoch
          @epoch = epoch
          changed = topo["primary"] && topo["primary"] != @address
          connect(topo["primary"]) if changed
          changed
        end

        def create_filter
          req = { "name" => @name, "exist_ok" => true }
          if @opts[:config]
            req["config"] = @opts[:config]
          else
            req["capacity"] = @opts[:size] || 1_000_000
            req["error_rate"] = @opts[:error_rate] || 0.01
            options = {}
            options["counting"] = true if @opts[:counting]
            req["options"] = options
          end
          # the constructor default covers the boot-time create too — a
          # fresh filter's existence is a write worth the quorum (the
          # server skips the barrier when this is a no-op attach)
          rpc("CreateFilter", durability(req, nil))
        end

        def counting?
          !!(@opts[:counting] || (@opts[:config] || {})["counting"] ||
             (@opts[:config] || {})[:counting])
        end

        # Per-call quorum wins over the constructor default; nil leaves the
        # server's --min-replicas-to-write in charge.
        def durability(payload, min_replicas)
          quorum = min_replicas || @min_replicas
          payload["min_replicas"] = quorum if quorum
          payload
        end

        MUTATING = %w[CreateFilter DropFilter InsertBatch DeleteBatch
                      Clear CFReserve CFAdd CFDel CMSInitByDim CMSIncrBy
                      TopKReserve TopKAdd].freeze

        def rpc(method, payload, no_retry: false)
          no_retry ||= method == "InsertBatch" && counting?
          retries = no_retry ? 0 : @max_retries
          # one rid per LOGICAL call — retries and the NOT_FOUND heal's
          # final retry reuse it; the server's DeleteBatch dedup keys on
          # it. A caller-provided rid wins (the cluster driver stamps one
          # BEFORE delegating here so its redirect/re-drive hops share it)
          payload = payload.merge("rid" => payload["rid"] || SecureRandom.hex(8))
          @last_rid = payload["rid"]
          # trace propagation (ISSUE 15): force capture under this rid on
          # every armed server the call touches. Off by default — the off
          # path ships byte-identical requests to pre-trace drivers.
          payload["trace"] = { "forced" => true } if @trace && !payload["trace"]
          attempt = 0
          shed_attempt = 0
          recreated = false
          redirected = false
          failed_over = false
          stale_refreshed = false
          begin
            # stamp the cached topology epoch on writes: a server under a
            # newer topology answers STALE_EPOCH and we refresh
            payload["epoch"] = @epoch if @epoch && MUTATING.include?(method)
            resp = rpc_once(method, payload)
            # track the op-log seq of our newest write — what #wait gates on
            @last_write_seq = resp["repl_seq"] if resp["repl_seq"]
            resp
          rescue GRPC::Unavailable
            # mid-failover the old primary is unreachable: re-resolve the
            # topology; a changed primary resets the budget once (the rid
            # makes a re-driven landed batch a dedup hit, never a double)
            if !failed_over && refresh_topology
              failed_over = true
              attempt = 0
              retry
            end
            raise if attempt >= retries
            sleep([0.2 * (2**attempt), 5.0].min * (0.5 + rand))
            attempt += 1
            retry
          rescue ServiceError => e
            if SHED_CODES.include?(e.code)
              # shed before execution — safe to replay any method; pace
              # off the server's retry_after_ms hint when it beats backoff
              raise if shed_attempt >= @max_retries
              delay = [0.2 * (2**shed_attempt), 5.0].min
              hint = e.details["retry_after_ms"]
              delay = [delay, hint / 1000.0].max if hint
              sleep(delay * (0.75 + rand / 2))
              shed_attempt += 1
              retry
            end
            if e.code == "STALE_EPOCH" && !stale_refreshed
              # our cached topology predates a failover: adopt + retry
              stale_refreshed = true
              @epoch = [@epoch || 0, e.details["epoch"] || 0].max
              refresh_topology
              retry
            end
            if e.code == "READONLY" && !redirected
              # the node we wrote to is a replica: follow the sentinels'
              # view (it wins — mid-failover the hint may be stale), or
              # the primary address its error advertises, MOVED-style
              redirected = true
              if refresh_topology
                retry
              end
              primary = e.details["primary"]
              if primary && primary != @address
                connect(primary)
                retry
              end
              raise
            end
            # A restarted server has not seen the filter yet: re-create it
            # (restores the newest checkpoint), then retry the op once.
            raise unless e.code == "NOT_FOUND" &&
                         method != "CreateFilter" && !recreated
            recreated = true
            create_filter
            retry
          end
        end

        def rpc_once(method, payload)
          raw = @stub.request_response(
            "/#{SERVICE}/#{method}",
            payload.to_msgpack,
            IDENTITY,
            IDENTITY
          )
          resp = MessagePack.unpack(raw)
          unless resp["ok"]
            err = resp["error"] || {}
            raise ServiceError.new(
              err["code"] || "UNKNOWN", err["message"], err["details"]
            )
          end
          resp
        end

        # Server packs hits MSB-first (numpy packbits); n trailing pad bits.
        def unpack_bits(bytes, n)
          out = []
          bytes.each_byte do |b|
            7.downto(0) { |i| out << (((b >> i) & 1) == 1) }
          end
          out.first(n)
        end
      end
    end
  end
end
