# Driver::Jax — the :jax execution driver for Redis::Bloomfilter.
#
# Parity: plugs into the reference's driver-selection boundary
# (SURVEY.md §1 L2: ":ruby / :lua -> new :jax"; BASELINE.json north star).
# Same duck-typed contract as the :ruby and :lua drivers — #insert,
# #include?, #clear — plus the batch surface the north star adds:
# #insert_batch and #include_batch?. Instead of issuing SETBIT/GETBIT (or
# EVALSHA) against Redis, every call ships key batches over gRPC to the
# colocated tpubloom JAX process, which holds the bit array in TPU HBM and
# checkpoints it back to Redis in the reference's own bitmap format (so a
# :ruby-driver reader still works against the checkpoint).
#
# Wire format: gRPC unary calls on /tpubloom.BloomService/<Method> with
# msgpack-encoded maps (see tpubloom/server/protocol.py — the environment
# that generated the server has no protoc codegen, and msgpack-ruby is
# ubiquitous). Requires gems: grpc, msgpack.
#
# NOTE: written against the documented server protocol but UNTESTED in the
# build environment (no Ruby toolchain in the image); exercised end-to-end
# via the Python client, which speaks the identical wire format.

require "grpc"
require "msgpack"

class Redis
  class Bloomfilter
    module Driver
      class Jax
        SERVICE = "tpubloom.BloomService".freeze
        METHODS = %w[
          Health CreateFilter DropFilter ListFilters
          InsertBatch QueryBatch DeleteBatch Clear Stats Checkpoint
        ].freeze

        IDENTITY = proc { |bytes| bytes }

        # opts mirrors the reference constructor options plus:
        #   :address       - "host:port" of the tpubloom server (default
        #                    127.0.0.1:50051)
        #   :size          - expected capacity (n)
        #   :error_rate    - desired false-positive probability
        #   :key_name      - filter name (also the Redis checkpoint key)
        #   :counting      - use the counting variant (enables #delete)
        def initialize(opts = {})
          @opts = opts
          @name = opts[:key_name] || "tpubloom"
          address = opts[:address] || "127.0.0.1:50051"
          @stub = GRPC::ClientStub.new(address, :this_channel_is_insecure)
          create_filter
        end

        def insert(key)
          insert_batch([key])
        end

        def insert_batch(keys)
          rpc("InsertBatch", "name" => @name, "keys" => keys.map(&:to_s))
          true
        end

        def include?(key)
          include_batch?([key]).first
        end

        # Returns an array of booleans, one per key.
        def include_batch?(keys)
          resp = rpc("QueryBatch", "name" => @name, "keys" => keys.map(&:to_s))
          unpack_bits(resp["hits"], resp["n"])
        end

        def delete(key)
          rpc("DeleteBatch", "name" => @name, "keys" => [key.to_s])
          true
        end

        def clear
          rpc("Clear", "name" => @name)
          true
        end

        def stats
          rpc("Stats", "name" => @name)["stats"]
        end

        def checkpoint
          rpc("Checkpoint", "name" => @name, "wait" => true)["seq"]
        end

        private

        def create_filter
          req = { "name" => @name, "exist_ok" => true }
          if @opts[:config]
            req["config"] = @opts[:config]
          else
            req["capacity"] = @opts[:size] || 1_000_000
            req["error_rate"] = @opts[:error_rate] || 0.01
            options = {}
            options["counting"] = true if @opts[:counting]
            req["options"] = options
          end
          rpc("CreateFilter", req)
        end

        def rpc(method, payload)
          raw = @stub.request_response(
            "/#{SERVICE}/#{method}",
            payload.to_msgpack,
            IDENTITY,
            IDENTITY
          )
          resp = MessagePack.unpack(raw)
          unless resp["ok"]
            err = resp["error"] || {}
            raise "tpubloom #{err['code'] || 'UNKNOWN'}: #{err['message']}"
          end
          resp
        end

        # Server packs hits MSB-first (numpy packbits); n trailing pad bits.
        def unpack_bits(bytes, n)
          out = []
          bytes.each_byte do |b|
            7.downto(0) { |i| out << (((b >> i) & 1) == 1) }
          end
          out.first(n)
        end
      end
    end
  end
end
